"""ccaudit — project-specific static analysis for the threaded reconciler fleet.

The reference repo's CI leaned on golangci-lint plus a vacuously green
``go test ./...`` (SURVEY.md §4); our ``make lint`` was a bare
``compileall``. Meanwhile simlab made this a genuinely concurrent system
(shared watch pump, bounded worker pool, leader flaps), and the defect
classes that fleet-scale scenarios hit first — ABBA deadlocks, silent
exception swallows, blocking calls under a lock — are exactly the ones a
compiler can't see but an AST walk can.

ccaudit is that walk. The rules (docs/analysis.md has the full contract):

``raw-acquire``
    Locks are acquired via ``with``; a bare ``.acquire()`` is flagged
    unless a ``try/finally`` in the same function releases the same lock.
``lock-order``
    A global lock-order graph is built from nested ``with`` blocks plus
    **transitive call summaries over the whole-program call graph**
    (``callgraph.py``, v3): a call made while a lock is held orders that
    lock ahead of every lock the callee's closure acquires, across
    modules and up to the depth bound (``--call-depth`` overrides); any
    cycle (a potential ABBA deadlock) is reported.
``blocking-under-lock``
    ``time.sleep``, subprocess, socket/HTTP, and executor waits inside a
    lock's ``with`` body are flagged — lexically, and (v3) transitively
    at any call under the lock whose closure reaches a blocking site.
``label-literal``
    Hard-coded ``tpu.google.com/...`` protocol strings belong in
    ``labels.py`` only; everywhere else must import the constant.
``swallow``
    ``except Exception``/``BaseException``/bare ``except`` bodies must
    re-raise, log, or use the bound exception — or carry an explicit
    ``# ccaudit: allow-swallow(reason)`` pragma.
``metric-name``
    Every metric name has exactly one Counter/Gauge/Histogram/
    HistogramVec declaration; ``tpu_cc_*`` string literals used anywhere
    else must match a declared name (two differently-bucketed
    expositions under one name would corrupt aggregation — obs.py's
    ``kube_throttle_wait_histogram`` docstring is the founding charter).

v2 grew the lexical walker into a flow-sensitive protocol analyzer
(``dataflow.py`` is the reusable core, ``manifests.py`` the non-AST
pass — docs/analysis.md §v2):

``protocol-literal``
    Raw mode/state strings (``"on"``/``"off"``/``"devtools"``/``"ici"``/
    ``"failed"``) flowing into label/annotation write APIs must come from
    ``modes.py``/``labels.py`` constants — tracked through local
    assignment and (v3) transitive cross-module sink summaries over the
    call graph, with the old same-module terminal-name match kept as the
    fallback for unresolvable receivers.
``unvalidated-mode``
    A mode-label value read off a k8s object dict must pass through
    ``parse_mode`` before reaching engine/subprocess/device-call sinks.
``mode-exhaustive``
    ``if``/``elif`` chains and dict dispatches over ``Mode`` must cover
    every member or end in an else that raises.
``protocol-liveness``
    Every key-shaped constant ``labels.py`` exports needs at least one
    writer and one reader across the tree (externally-written keys are
    pragma-annotated).
``manifest-drift``
    ``deployments/**`` and ``scenarios/*.json`` must speak exactly the
    protocol ``labels.py``/``modes.py`` export — unknown keys, unknown
    modes, and a CRD mode enum differing from ``VALID_MODES`` all fail.

v3 made the analyzer whole-program: ``callgraph.py`` (nominal
project-wide call graph — module attributes, ``self.``-methods, nested
defs, typed locals; cycle-safe, depth-bounded by
``callgraph.DEPTH_LIMIT`` with ``--call-depth`` as the escape hatch)
replaces every "one hop, same module" summary, and two new passes ride
on it (docs/analysis.md §v3):

``race-lockset``
    ``threads.py`` infers thread roots (``threading.Thread`` targets,
    executor ``submit`` callables incl. the flipexec worker,
    ``*RequestHandler`` ``do_*`` methods, parameter-linked callbacks);
    ``lockset.py`` runs an Eraser-style lockset pass over
    ``self.``-attributes and mutable module globals shared across
    contexts — a shared location written with an empty or inconsistent
    guarding lockset is a finding. Reads-only sharing,
    init-before-spawn, and caller-held locks (the ``_locked`` suffix
    convention) are recognized; deliberate benign races carry
    ``# ccaudit: allow-race-lockset(reason)``.

v4 taught the analyzer the event-loop concurrency model
(``asyncflow.py`` over the same call graph — docs/analysis.md §v4),
because since ISSUE 13 the coordination substrate is an asyncio core
the thread passes could not see into:

``await-atomicity``
    An ``await`` in an ``async def`` is a visible interleaving point:
    read-check-write of a ``self.``-attribute or module global spanning
    an await without a common *asyncio* lock (caller-held ⋂-fixpoint
    included) fires; ``allow-await-atomicity(reason)`` documents a
    single-loop invariant.
``lock-across-await``
    A *threading* lock held at an await parks the entire loop.
``loop-affinity`` / ``loop-self-deadlock``
    Loop-owned state (attrs of the async-core classes written in
    coroutines, or holding asyncio queues/futures/tasks) touched from
    sync land — a sync method not provably loop-confined via the call
    graph, or an attribute chain through a typed reference anywhere in
    the tree — fires ``loop-affinity``; ``bridge.call``/``gather`` or a
    bridge future's ``.result()`` from INSIDE a coroutine is
    ``loop-self-deadlock`` at error severity.
``orphan-task`` / ``async-exception``
    Dropped ``create_task``/``ensure_future`` handles and discarded
    coroutine calls fire; in the async core, an ``except`` that exits a
    request path without settling/propagating pending entries (the
    gather-settles-everything contract, docs/io.md) is flagged via a
    settle-sink summary over the call graph.

v5 taught the analyzer the JAX dispatch model (``jitflow.py`` over the
same call graph and caller-held ⋂-fixpoint — docs/analysis.md §v5),
ahead of the multi-host planner refactor (ROADMAP item 1) that
multiplies the dispatch surface:

``retrace-hazard``
    Shape/static arguments of jitted callables and jit factories are
    classified on a CONST ⊑ BUCKETED ⊑ DYNAMIC provenance lattice;
    anything not derived from the sanctioned bucket ladder
    (``bucket_nodes``/``bucket_pools``, a snapshot's ``.bucket``)
    at a geometry/static position is a silent multi-second recompile
    in the tick path. ``allow-retrace(reason)`` suppresses.
``host-sync-in-hot-path``
    Implicit device→host transfers (``float()``/``int()``/``bool()``/
    ``np.asarray``/``.item()``/iteration on jit outputs) and
    ``.block_until_ready()`` reachable from reconcile/scan call paths
    stall the controller thread; ``jax.device_get`` is the sanctioned
    explicit transfer. bench/scripts/simlab exempt;
    ``allow-host-sync(reason)`` suppresses.
``unserialized-dispatch``
    Every dispatch of a ``shard_map``-wrapped collective program must
    hold ``_DISPATCH_LOCK`` (plan.py:746 — PR 7's rendezvous stalls),
    lexically or via the caller-held ⋂-fixpoint. Error severity.
``donation-violation``
    An argument at a ``donate_argnums`` position read after the
    donating call sees freed device memory (statement-order).
``tracer-leak``
    Writes to ``self.``/module globals inside traced bodies run once
    per (re)trace, not per call; ``if``/``while`` on a traced
    parameter is a trace-time TypeError. Static/keyword-only config
    parameters and ``is None`` defaulting are exempt.

v6 taught the analyzer overload discipline (``resourceflow.py`` over
the same parse + call graph — docs/analysis.md §v6), the down-payment
on ROADMAP item 3: a saturated control plane must degrade deliberately,
and these five families make the disciplines un-regressable:

``unbounded-queue``
    Queue/asyncio.Queue family constructors without a positive
    ``maxsize``, ``queue.SimpleQueue`` anywhere, and cross-context
    deques (``self.``/module/class stores) without ``maxlen``. The aio
    writer backlog was the seeded true positive — now bounded behind
    ``TPU_CC_KUBE_QUEUE`` with ``tpu_cc_kube_queue_rejected_total``
    accounting. Error severity; ``allow-unbounded-queue(reason)``.
``missing-deadline``
    A BOUNDED/UNBOUNDED timeout lattice over the reconcile/scan/flip
    closure (widened with the k8s I/O core): ``.result()``,
    ``concurrent.futures.wait``, subprocess, requests, ``select`` and
    awaited stream/semaphore/queue suspensions must carry a deadline on
    every caller path — ``wait_for`` wrapping, deadline-clamp
    arithmetic, and timeout-forwarding parameters resolved through a
    caller-path ⋂-fixpoint all count.
``retry-discipline``
    A retry loop around an I/O sink must show all three legs — an
    attempt/deadline cap, backoff growth, jitter — lexically or via the
    called helper's call-graph closure; the two-attempt replay shape is
    exempt.
``resource-leak``
    Acquire/release path check over sockets, files, executors,
    tempfiles, subprocesses: close under ``try/finally`` or a context
    manager on all exception paths, or a visible ownership transfer;
    ``self.``-attribute acquisitions need a close site somewhere in the
    module.
``stop-aware-wait``
    Blocking waits on controller threads must ride the ``_stop``-Event
    convention (SIGTERM must never hang a flip): ``time.sleep``,
    stopless no-timeout ``.wait()``/queue ``.get()``, and timed waits
    in loops that never consult the stop signal all fire — error
    severity when the wait sits in a loop.

Findings are gated against ``analysis/baseline.json`` so CI fails only on
*new* findings; stale baseline entries (the code they suppressed moved or
was fixed) also fail, so the baseline can only burn down.

Run it: ``python -m tpu_cc_manager.analysis`` (wired into ``make lint``);
``--sarif PATH`` writes a SARIF 2.1.0 log CI uploads for inline PR
annotations; ``--files a.py b.py`` is the changed-files mode
(``make lint-fast``): the analysis stays whole-program but the report
is restricted to the named files, and manifests are skipped.
"""

from tpu_cc_manager.analysis.core import (  # noqa: F401
    Finding,
    analyze_paths,
    analyze_source,
    repo_root,
)
from tpu_cc_manager.analysis.baseline import (  # noqa: F401
    BASELINE_PATH,
    diff_against_baseline,
    load_baseline,
    write_baseline,
)

RULES = (
    "raw-acquire",
    "lock-order",
    "blocking-under-lock",
    "label-literal",
    "swallow",
    "metric-name",
    # v2 — the flow-sensitive protocol families
    "protocol-literal",
    "unvalidated-mode",
    "mode-exhaustive",
    "protocol-liveness",
    "manifest-drift",
    # v3 — the whole-program concurrency pass
    "race-lockset",
    # v4 — the async-aware families (asyncflow.py)
    "await-atomicity",
    "lock-across-await",
    "loop-affinity",
    "loop-self-deadlock",
    "orphan-task",
    "async-exception",
    # v5 — the JAX-dispatch families (jitflow.py)
    "retrace-hazard",
    "host-sync-in-hot-path",
    "unserialized-dispatch",
    "donation-violation",
    "tracer-leak",
    # v6 — the resource & overload-discipline families (resourceflow.py)
    "unbounded-queue",
    "missing-deadline",
    "retry-discipline",
    "resource-leak",
    "stop-aware-wait",
)
