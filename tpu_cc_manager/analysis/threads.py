"""ccaudit thread-root inference (v3).

The runtime is genuinely concurrent: ~10 long-lived ``threading.Thread``
roots (fleet watch, policy CR/node watchers, webhook serve + cert
reload, agent event recorder, watch pump, simlab replicas), the
flipexec executor workers, and ThreadingHTTPServer per-request handler
threads. This module recovers those roots *statically* from the
per-function records ``rules.audit_module`` collects:

- every resolvable ``threading.Thread(target=…)`` (``self._run``,
  ``fleet.run`` through a typed local, a nested ``worker`` def);
- every executor ``…submit(fn, …)`` first argument — including the
  flipexec worker entry (``pool.submit(worker, item)``);
- ``do_*`` methods of ``*RequestHandler`` subclasses (the stdlib spawn
  site is invisible, but ThreadingHTTPServer runs each request on its
  own thread).

Escaped callbacks (a ``self.``-method handed to a runner, stored in a
callback table, or routed through a queue) are NOT separate roots:
``callgraph._link_param_callbacks`` gives them call-graph edges from
the site that actually *calls* them, so they inherit the right root's
context — flipexec's ``flip_one`` lands under the submit-root worker,
while a callback driven synchronously stays in its caller's context.

A root is ``self_concurrent`` when it races *itself* — spawned in a
loop, submitted to an executor, or a per-request handler. The lockset
pass (``lockset.py``) treats functions reachable from two distinct
roots — or from one self-concurrent root — as multi-threaded.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Set

from tpu_cc_manager.analysis.callgraph import CallGraph
from tpu_cc_manager.analysis.rules import ModuleAudit

#: Pseudo-root id for code not reachable from any inferred thread root —
#: the spawning/main thread's context.
MAIN = "<main>"

#: Pseudo-root id for the bridge event-loop thread: every ``async def``
#: executes here (the process runs ONE loop — aio_bridge's singleton).
#: The v4 asyncflow pass seeds its loop-confinement fixpoint from these.
LOOP = "<loop>"

#: kinds in confidence order (kept on merge)
_KIND_RANK = {"thread": 0, "submit": 1, "handler": 2}


@dataclass
class ThreadRoot:
    qual: str  #: entry function
    kind: str  #: "thread" | "submit" | "handler"
    file: str
    line: int
    #: True when instances of this root run concurrently with each
    #: other (loop-spawned, executor-submitted, per-request)
    self_concurrent: bool


def infer_roots(
    audits: Sequence[ModuleAudit], graph: CallGraph
) -> Dict[str, ThreadRoot]:
    """qual -> root, merged across spawn sites (strongest kind wins,
    ``self_concurrent`` is sticky)."""
    roots: Dict[str, ThreadRoot] = {}

    def add(root: ThreadRoot) -> None:
        cur = roots.get(root.qual)
        if cur is None:
            roots[root.qual] = root
            return
        cur.self_concurrent = cur.self_concurrent or root.self_concurrent
        if _KIND_RANK[root.kind] < _KIND_RANK[cur.kind]:
            cur.kind = root.kind

    for audit in audits:
        for fn in audit.functions:
            if fn.handler_root:
                add(ThreadRoot(
                    qual=fn.qual, kind="handler",
                    file=audit.module.relpath, line=fn.line,
                    self_concurrent=True,
                ))
            for ref in fn.refs:
                qual = graph.resolve_parts(
                    audit.dotted,
                    ref.cls if ref.cls is not None else fn.cls,
                    attr_self=ref.attr_self,
                    bare=ref.bare,
                    dotted=ref.recv_class or ref.resolved,
                    scope=fn.scope,
                    scope_kinds=fn.scope_kinds,
                    fn_name=fn.name,
                )
                if qual is None:
                    continue
                add(ThreadRoot(
                    qual=qual, kind=ref.kind,
                    file=audit.module.relpath, line=ref.line,
                    self_concurrent=ref.self_concurrent
                    or ref.kind == "submit",
                ))
    return roots


def contexts(
    graph: CallGraph, roots: Dict[str, ThreadRoot]
) -> Dict[str, Set[str]]:
    """fn qual -> set of root quals it is reachable from. Functions in
    no root's closure belong to the ``MAIN`` pseudo-context (the
    lockset pass fills that in per access).

    A root that lies wholly inside another root's closure (``scan_once``
    is spawned as a one-shot bench thread AND called from the run loop)
    is *subsumed*: labelling its closure twice would make one code path
    look like two racing threads. Self-concurrent roots are never
    subsumed — they race themselves regardless of who else calls them.
    Mutually-reachable roots (two thread entries that call into each
    other) subsume each other symmetrically, so the smallest qual of
    each mutual group is kept — dropping the whole group would make
    genuinely two-threaded code look single-threaded.
    """
    reach = {q: graph.reachable([q]) for q in roots}

    def strictly_subsumed(q: str) -> bool:
        return any(
            q in reach[o] and o not in reach[q]
            for o in roots if o != q
        )

    effective = []
    for q, r in roots.items():
        if r.self_concurrent:
            effective.append(q)
            continue
        if strictly_subsumed(q):
            continue
        mutual = [
            o for o in roots
            if o != q and q in reach[o] and o in reach[q]
            and not strictly_subsumed(o)
        ]
        # mutual group: kept only by its smallest non-subsumed member,
        # which becomes self-concurrent — the group is ≥2 OS threads
        # executing one shared closure, exactly the race-with-itself
        # shape (dropping the label would hide it entirely)
        if any(o < q for o in mutual):
            continue
        if mutual:
            r.self_concurrent = True
        effective.append(q)
    ctx: Dict[str, Set[str]] = {}
    for root_qual in effective:
        for q in reach[root_qual]:
            ctx.setdefault(q, set()).add(root_qual)
    return ctx


def async_roots(audits: Sequence[ModuleAudit]) -> Set[str]:
    """Quals of every ``async def`` — each is an entry point onto the
    process's one event loop (the ``LOOP`` pseudo-context). The v4
    asyncflow pass seeds loop-confinement from this set: a sync
    function all of whose resolved callers live here (transitively) is
    provably loop-confined."""
    return {
        fn.qual
        for audit in audits
        for fn in audit.functions
        if fn.is_async
    }


def shared_functions(
    graph: CallGraph, roots: Dict[str, ThreadRoot]
) -> List[str]:
    """Quals reachable from more than one root (diagnostics/tests)."""
    ctx = contexts(graph, roots)
    return sorted(q for q, c in ctx.items() if len(c) > 1)
