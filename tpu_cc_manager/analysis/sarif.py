"""SARIF 2.1.0 output for ccaudit (v3 satellite).

``python -m tpu_cc_manager.analysis --sarif ccaudit.sarif`` writes the
scan as a Static Analysis Results Interchange Format log alongside the
normal text/JSON output, so the CI ``ccaudit`` job can upload it and
findings annotate PR diffs inline (GitHub code scanning understands
SARIF natively).

The emitted subset is deliberately small and stable:

- one ``run`` with the ``ccaudit`` tool driver and one ``rule`` entry
  per rule id seen in the scan;
- one ``result`` per finding — ``level`` for *new* findings is the
  finding's severity (``error`` for every pre-v4 rule and
  ``loop-self-deadlock``; ``warning`` for the v4 asyncflow advisory
  families) and ``note`` for baselined ones, which additionally carry a
  ``suppressions`` entry (``kind: external``) so code-scanning UIs show
  them as suppressed rather than open;
- physical locations are repo-relative with ``uriBaseId: SRCROOT``.

``validate_sarif`` is the structural contract the test suite enforces —
the container has no jsonschema package, so the required-shape checks
are spelled out by hand against the 2.1.0 spec.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from tpu_cc_manager.analysis.core import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

#: one-line help per rule id, mirrored from docs/analysis.md
_RULE_HELP = {
    "raw-acquire": "Locks are acquired via `with`, or paired with a "
    "try/finally release.",
    "lock-order": "Potential ABBA deadlock: locks acquired in opposite "
    "orders across the transitive call graph.",
    "blocking-under-lock": "Blocking call (sleep/subprocess/socket/"
    "executor wait) reachable while a lock is held.",
    "label-literal": "Hard-coded protocol label literal outside "
    "labels.py.",
    "swallow": "Broad except handler that neither re-raises, logs, nor "
    "uses the bound exception.",
    "metric-name": "Metric name without exactly one declaration.",
    "protocol-literal": "Raw mode/state literal flowing into a "
    "label-write API.",
    "unvalidated-mode": "Mode label value reaching a device/subprocess "
    "sink without parse_mode().",
    "mode-exhaustive": "Mode dispatch that does not cover every enum "
    "member.",
    "protocol-liveness": "labels.py constant with no writer or no "
    "reader in the scanned tree.",
    "manifest-drift": "Deploy manifests / scenarios speaking a "
    "different protocol than labels.py/modes.py.",
    "race-lockset": "Shared location written with an empty or "
    "inconsistent guarding lockset across thread contexts.",
    # v4 — the asyncflow families
    "await-atomicity": "Read-check-write of shared state spans an "
    "await without a common asyncio.Lock — other coroutines interleave "
    "at the suspension point.",
    "lock-across-await": "A threading lock is held at an await — the "
    "whole event loop queues behind the lock's next owner.",
    "loop-affinity": "Loop-owned state (conn pool, futures, queues) "
    "touched from sync land outside the bridge's sanctioned routes.",
    "loop-self-deadlock": "bridge.call/gather or a bridge future's "
    ".result() from the loop thread — the loop waits on work only the "
    "loop can run.",
    "orphan-task": "create_task/ensure_future handle dropped, or a "
    "coroutine-valued call discarded without ever being awaited.",
    "async-exception": "An except exits an async request path without "
    "settling or propagating its pending entries "
    "(gather-settles-everything contract).",
    "retrace-hazard": "Jitted callable or jit factory invoked with a "
    "shape/static argument not derived from the bucket ladder "
    "(bucket_nodes/bucket_pools) — each distinct value is a silent "
    "multi-second XLA recompile in the tick path.",
    "host-sync-in-hot-path": "Implicit device-to-host transfer "
    "(float()/int()/np.asarray/.item()/iteration on a jit output) or "
    "block_until_ready() on a reconcile/scan hot path — stalls the "
    "controller thread; batch through one explicit jax.device_get.",
    "unserialized-dispatch": "A shard_map collective dispatched without "
    "holding _DISPATCH_LOCK (plan.py's contract): concurrent dispatch "
    "interleaves XLA's all-reduce rendezvous and parks participants in "
    "multi-second stalls.",
    "donation-violation": "Argument at a donate_argnums position read "
    "after the donating call — its device buffer now belongs to XLA.",
    "tracer-leak": "Traced value stored to self./module globals (runs "
    "once per retrace, not per call) or used in a Python if/while "
    "inside a jitted body (TracerBoolConversionError).",
    "unbounded-queue": "Queue/deque/asyncio.Queue constructed without "
    "a positive bound on the package surface — overload becomes memory "
    "growth and unbounded latency instead of an honest rejection "
    "(the aio writer backlog rides TPU_CC_KUBE_QUEUE).",
    "missing-deadline": "Blocking call or await on the reconcile/scan/"
    "flip closure with no timeout/deadline on some caller path — a "
    "wedged peer stalls the drain-flip-verify loop forever; wrap in "
    "wait_for, pass a timeout, or clamp against a deadline.",
    "retry-discipline": "Retry loop around an I/O sink missing backoff "
    "growth, jitter, or an attempt/deadline cap — uncapped immediate "
    "retries synchronize into a thundering herd exactly when the "
    "server is saturated.",
    "resource-leak": "Acquired socket/file/executor/tempfile/process "
    "not released on all exception paths — close it under try/finally, "
    "use a context manager, or visibly transfer ownership.",
    "stop-aware-wait": "Blocking wait on a controller thread that no "
    "stop/shutdown signal can interrupt — ride the _stop Event "
    "(self._stop.wait(t)) so SIGTERM never hangs a flip.",
    "stale-baseline": "Baseline entry matching no current finding — "
    "delete it (the ratchet only burns down).",
}


def _result(finding: Finding, suppressed: bool) -> dict:
    # a NEW finding surfaces at the finding's own severity ("error" for
    # every pre-v4 rule and loop-self-deadlock; "warning" for the v4
    # advisory families) — baselined findings demote to "note" with a
    # suppression either way
    out: dict = {
        "ruleId": finding.rule,
        "level": "note" if suppressed else finding.severity,
        "message": {"text": finding.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": finding.file,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": max(1, finding.line),
                        "snippet": {"text": finding.text},
                    },
                }
            }
        ],
    }
    if suppressed:
        out["suppressions"] = [
            {
                "kind": "external",
                "justification": "baselined in "
                "tpu_cc_manager/analysis/baseline.json (the ratchet "
                "only burns down)",
            }
        ]
    return out


def to_sarif(
    new: Sequence[Finding],
    suppressed: Sequence[Finding],
    stale: Sequence[dict],
) -> dict:
    """Build the SARIF log dict for one ccaudit run. Stale baseline
    entries are reported as ``stale-baseline`` results so the gate's
    second failure mode annotates the PR too."""
    results: List[dict] = []
    rules_seen: Dict[str, None] = {}
    for f in new:
        results.append(_result(f, suppressed=False))
        rules_seen.setdefault(f.rule)
    for f in suppressed:
        results.append(_result(f, suppressed=True))
        rules_seen.setdefault(f.rule)
    for e in stale:
        rules_seen.setdefault("stale-baseline")
        results.append(
            {
                "ruleId": "stale-baseline",
                "level": "error",
                "message": {
                    "text": (
                        f"baseline entry for rule {e.get('rule')!r} "
                        "matches no current finding — delete it (or "
                        "--write-baseline)"
                    )
                },
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": str(e.get("file", "")),
                                "uriBaseId": "SRCROOT",
                            },
                            "region": {
                                "startLine": max(1, int(e.get("line", 1)))
                            },
                        }
                    }
                ],
            }
        )
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "ccaudit",
                        "informationUri": (
                            "https://github.com/tpu-cc-manager/"
                            "tpu-cc-manager/blob/main/docs/analysis.md"
                        ),
                        "rules": [
                            {
                                "id": rule,
                                "shortDescription": {
                                    "text": _RULE_HELP.get(rule, rule)
                                },
                            }
                            for rule in sorted(rules_seen)
                        ],
                    }
                },
                "columnKind": "utf16CodeUnits",
                "results": results,
            }
        ],
    }


def write_sarif(
    path: str,
    new: Sequence[Finding],
    suppressed: Sequence[Finding],
    stale: Sequence[dict],
) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(to_sarif(new, suppressed, stale), f, indent=1,
                  sort_keys=True)
        f.write("\n")


def validate_sarif(doc: dict) -> List[str]:
    """Structural validation against the SARIF 2.1.0 required shape
    (the container has no jsonschema package — the spec's MUSTs for the
    subset we emit are checked by hand). Returns a list of violations;
    empty means valid."""
    errors: List[str] = []

    def need(cond: bool, msg: str) -> None:
        if not cond:
            errors.append(msg)

    need(isinstance(doc, dict), "log must be an object")
    if not isinstance(doc, dict):
        return errors
    need(doc.get("version") == SARIF_VERSION,
         f"version must be {SARIF_VERSION!r}")
    runs = doc.get("runs")
    need(isinstance(runs, list) and len(runs) >= 1,
         "runs must be a non-empty array")
    for run in runs if isinstance(runs, list) else []:
        need(isinstance(run, dict), "run must be an object")
        if not isinstance(run, dict):
            continue
        driver = (run.get("tool") or {}).get("driver")
        need(isinstance(driver, dict), "run.tool.driver is required")
        if isinstance(driver, dict):
            need(isinstance(driver.get("name"), str) and driver["name"],
                 "driver.name must be a non-empty string")
            for rule in driver.get("rules", []):
                need(isinstance(rule.get("id"), str) and rule["id"],
                     "rule.id must be a non-empty string")
        rule_ids = {
            r.get("id")
            for r in (driver or {}).get("rules", [])
            if isinstance(r, dict)
        } if isinstance(driver, dict) else set()
        results = run.get("results", [])
        need(isinstance(results, list), "run.results must be an array")
        for res in results if isinstance(results, list) else []:
            need(isinstance(res.get("ruleId"), str),
                 "result.ruleId must be a string")
            need(res.get("level") in ("none", "note", "warning", "error"),
                 f"result.level invalid: {res.get('level')!r}")
            need(res.get("ruleId") in rule_ids,
                 f"result.ruleId {res.get('ruleId')!r} not declared in "
                 "driver.rules")
            msg = res.get("message")
            need(isinstance(msg, dict) and isinstance(msg.get("text"), str),
                 "result.message.text is required")
            for loc in res.get("locations", []):
                phys = loc.get("physicalLocation", {})
                art = phys.get("artifactLocation", {})
                need(isinstance(art.get("uri"), str),
                     "artifactLocation.uri must be a string")
                region = phys.get("region", {})
                start = region.get("startLine")
                need(isinstance(start, int) and start >= 1,
                     "region.startLine must be a positive integer")
            for sup in res.get("suppressions", []):
                need(sup.get("kind") in ("inSource", "external"),
                     f"suppression.kind invalid: {sup.get('kind')!r}")
    return errors
