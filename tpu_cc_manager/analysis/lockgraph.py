"""ccaudit lock-order graph: ABBA-cycle detection over ``with`` nesting.

Nodes are module/class-qualified lock names (``agent.Agent._event_lock``).
Edges come from two sources:

- **lexical nesting** — ``with a:`` containing ``with b:`` adds a→b;
- **a transitive call summary** (v3) — a call made while ``a`` is held,
  resolved through the whole-program call graph (``callgraph.py``:
  module attributes, ``self.``-methods, nested defs, typed locals),
  adds a→b for every lock ``b`` the callee's transitive closure
  acquires while holding nothing. The closure is cycle-safe and
  depth-bounded (``callgraph.DEPTH_LIMIT``, ``--call-depth`` on the
  CLI is the escape hatch; ``--call-depth 0`` restricts summaries to
  the direct callee — the old v2 one-hop horizon).

All modules' edges land in one global graph, so an inversion between,
say, ``engine`` and ``simlab`` helpers shows up even when each side of
the cycle lives behind two calls in different modules. A cycle means two
threads can acquire the same locks in opposite orders — the classic ABBA
deadlock that only fires under fleet-scale contention.

A self-edge (a lock re-acquired while already held, lexically or through
any resolved call chain) is reported unless the lock is known reentrant
(``RLock``/``Condition``).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Optional, Sequence, Set, Tuple

from tpu_cc_manager.analysis.core import Finding

if TYPE_CHECKING:  # pragma: no cover - typing only (no runtime cycle risk)
    from tpu_cc_manager.analysis.callgraph import CallGraph
    from tpu_cc_manager.analysis.rules import LockSite, ModuleAudit

RULE = "lock-order"


def _edges(
    audits: Sequence["ModuleAudit"], graph: Optional["CallGraph"]
) -> Dict[Tuple[str, str], "LockSite"]:
    """(outer_qual, inner_qual) -> evidence LockSite of the inner acquire,
    keeping the lexically-first evidence per edge for stable output."""
    edges: Dict[Tuple[str, str], "LockSite"] = {}

    def add(a: str, b: str, evidence: "LockSite") -> None:
        key = (a, b)
        cur = edges.get(key)
        if cur is None or (evidence.file, evidence.line) < (cur.file, cur.line):
            edges[key] = evidence

    for audit in audits:
        for outer, inner in audit.lock_edges:
            add(outer.qual, inner.qual, inner)
        if graph is None:
            continue
        # v2-parity fallback for receivers the graph cannot resolve:
        # same-module functions matched by terminal name, direct entry
        # locks only (one hop, no transitivity — the old horizon is a
        # strict floor, same contract as dataflow's fallback)
        by_name: Dict[str, List["LockSite"]] = {}
        for fn in audit.functions:
            if fn.entry_locks:
                by_name.setdefault(fn.name, []).extend(fn.entry_locks)
        for fn in audit.functions:
            for call in fn.calls:
                if call.held is None:
                    continue
                callee = graph.resolve_call(audit, fn, call)
                if callee is not None:
                    for site in graph.transitive_entry_locks(callee):
                        add(call.held.qual, site.qual, site)
                elif call.term is not None:
                    for site in by_name.get(call.term, ()):
                        add(call.held.qual, site.qual, site)
    return edges


def _sccs(nodes: Sequence[str], adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan's strongly-connected components, iterative (analyzer input
    is arbitrary user code — no recursion-depth bets)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(sorted(comp))
    return out


def order_findings(
    audits: Sequence["ModuleAudit"], graph: Optional["CallGraph"] = None
) -> List[Finding]:
    by_relpath = {a.module.relpath: a.module for a in audits}
    edges = _edges(audits, graph)

    findings: List[Finding] = []

    def emit(evidence: "LockSite", message: str) -> None:
        mod = by_relpath.get(evidence.file)
        if mod is not None and mod.suppressed(RULE, evidence.line):
            return
        findings.append(
            Finding(
                file=evidence.file,
                line=evidence.line,
                rule=RULE,
                message=message,
                text=evidence.text,
            )
        )

    # direct non-reentrant re-acquisition (with a: ... with a:)
    for (a, b), evidence in sorted(edges.items()):
        if a == b and not evidence.reentrant:
            emit(
                evidence,
                f"{evidence.display} re-acquired while already held — "
                "a non-reentrant lock deadlocks against itself",
            )

    # two-lock inversions: both a->b and b->a exist
    reported: Set[Tuple[str, str]] = set()
    for (a, b), evidence in sorted(edges.items()):
        if a >= b or (b, a) not in edges:
            continue
        back = edges[(b, a)]
        reported.add((a, b))
        emit(
            evidence,
            f"potential ABBA deadlock: {a} and {b} are acquired in both "
            f"orders ({a}→{b} here; {b}→{a} at "
            f"{back.file}:{back.line})",
        )

    # longer cycles with no internal 2-cycle (a->b->c->a): one finding
    # per strongly-connected component, anchored at its first edge
    adj: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        if a != b:
            adj.setdefault(a, set()).add(b)
    nodes = sorted(set(adj) | {b for tgts in adj.values() for b in tgts})
    for comp in _sccs(nodes, adj):
        if len(comp) < 2:
            continue
        pairs = {(a, b) for a in comp for b in comp if (a, b) in reported}
        if pairs:
            continue  # already reported as inversion(s)
        comp_edges = sorted(
            (k, v) for k, v in edges.items()
            if k[0] in comp and k[1] in comp and k[0] != k[1]
        )
        (a, b), evidence = comp_edges[0]
        emit(
            evidence,
            "potential ABBA deadlock: lock-order cycle through "
            + " → ".join(comp)
            + " (first edge here)",
        )
    return findings
