"""ccaudit core: findings, pragma parsing, module scanning, orchestration.

The per-module AST walk lives in ``rules.py``; the cross-module passes
(lock-order cycles, metric-name registry) consume the per-module results
here so a single ``analyze_paths()`` call yields one flat finding list.
"""

from __future__ import annotations

import ast
import hashlib
import io
import os
import pickle
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: ``# ccaudit: allow-<rule>(<reason>)`` — the reason is mandatory; a
#: suppression with no recorded why is just a finding wearing a disguise.
PRAGMA_RE = re.compile(r"#\s*ccaudit:\s*allow-([a-z][a-z-]*)\s*\(\s*([^)]+?)\s*\)")

#: What the analyzer scans by default, relative to the repo root — the
#: same surface ``make lint`` compiles. Tests are deliberately excluded:
#: fixtures legitimately hard-code wire-protocol strings to assert them.
DEFAULT_TARGETS = ("tpu_cc_manager", "scripts", "bench.py", "__graft_entry__.py")

_EXCLUDE_DIRS = {"__pycache__", "native", "tests", ".git"}


@dataclass(frozen=True, order=True)
class Finding:
    file: str  #: repo-relative posix path
    line: int
    rule: str
    message: str
    text: str  #: stripped source line — the baseline's drift detector
    #: SARIF level for a NEW finding: "error" (the historical default —
    #: every pre-v4 rule gates hard) or "warning" (the v4 asyncflow
    #: advisory families; ``loop-self-deadlock`` stays "error": a
    #: ``.result()`` on the loop thread is a guaranteed deadlock, not a
    #: judgement call). The baseline gate ignores severity — any new
    #: finding fails the ratchet either way.
    severity: str = "error"

    def key(self) -> Tuple[str, str, int, str]:
        return (self.rule, self.file, self.line, self.text)

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "text": self.text,
        }


class Module:
    """One parsed source file plus its pragma map and line cache."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=relpath)
        self._lines = source.splitlines()
        self.pragmas = _parse_pragmas(source)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self._lines):
            return self._lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        """A pragma suppresses its rule on its own line or the line below
        (i.e. write the pragma on the flagged line or just above it)."""
        for ln in (lineno, lineno - 1):
            if rule in self.pragmas.get(ln, ()):
                return True
        return False


def _parse_pragmas(source: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            for m in PRAGMA_RE.finditer(tok.string):
                out.setdefault(tok.start[0], set()).add(m.group(1))
    except tokenize.TokenError:
        pass  # unparseable tail; ast.parse already vetted the file
    return out


def collect_imports(tree: ast.Module) -> Dict[str, str]:
    """alias -> real dotted prefix for one module: ``sp`` →
    ``subprocess``, ``sleep`` → ``time.sleep``, ``L`` →
    ``tpu_cc_manager.labels``; ``import http.client`` binds the local
    name ``http``. The ONE import fold every rule family shares."""
    imports: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    imports[alias.asname] = alias.name
                else:
                    top = alias.name.split(".")[0]
                    imports[top] = top
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                imports[alias.asname or alias.name] = (
                    f"{node.module}.{alias.name}"
                )
    return imports


def dotted(expr: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def resolve_dotted(expr: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Dotted path with import aliases folded in (``sp`` → ``subprocess``,
    ``L`` → ``tpu_cc_manager.labels``) — the ONE resolution fold every
    rule family shares, so they can never disagree on what a name means."""
    path = dotted(expr)
    if path is None:
        return None
    head, _, rest = path.partition(".")
    real = imports.get(head)
    if real:
        return f"{real}.{rest}" if rest else real
    return path


def module_dotted(relpath: str) -> str:
    """Repo-relative path → importable dotted module path
    (``tpu_cc_manager/device/fake.py`` → ``tpu_cc_manager.device.fake``;
    a package ``__init__.py`` maps to the package itself). The call
    graph keys every function by this, so two ``fake.py`` files in
    different packages can never collide."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def repo_root() -> str:
    """The repo root is two levels above this package (…/tpu_cc_manager/
    analysis/core.py); resolving from ``__file__`` keeps the CLI working
    from any cwd."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def iter_python_files(root: str, targets: Sequence[str]) -> List[str]:
    """Repo-relative posix paths of every .py file under ``targets``.

    A target that matches no Python files (typo, renamed surface) is a
    loud error — a gate that quietly stops scanning is worse than none.
    """
    out: List[str] = []
    for target in targets:
        found = []
        full = os.path.join(root, target)
        if os.path.isfile(full):
            if full.endswith(".py"):
                found.append(target.replace(os.sep, "/"))
        else:
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _EXCLUDE_DIRS
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, fn), root
                        )
                        found.append(rel.replace(os.sep, "/"))
        if not found:
            raise FileNotFoundError(
                f"ccaudit scan target {target!r} matched no Python files "
                f"under {root}"
            )
        out.extend(found)
    return sorted(set(out))


def on_default_surface(relpath: str) -> bool:
    """Whether a repo-relative path belongs to the default scan surface.
    The ``--files`` mode uses this to drop changed files the merge gate
    never scans (tests hard-code wire-protocol strings to assert them;
    flagging a fixture the full run would never see is pure noise)."""
    rel = relpath.replace(os.sep, "/")
    if any(part in _EXCLUDE_DIRS for part in rel.split("/")[:-1]):
        return False
    return any(
        rel == target or rel.startswith(target + "/")
        for target in DEFAULT_TARGETS
    )


#: where the opt-in per-module fact cache lives, relative to the repo
#: root (gitignored; ``--cache`` / ``make lint-fast`` turn it on)
CACHE_DIR_NAME = ".ccaudit_cache"


def analyzer_version_hash() -> str:
    """Digest of the analyzer's own sources. Cache keys embed it, so
    editing ANY rule module invalidates every cached fact — the cache
    can never serve facts a different analyzer computed."""
    h = hashlib.sha256()
    pkg = os.path.dirname(os.path.abspath(__file__))
    for fn in sorted(os.listdir(pkg)):
        if fn.endswith(".py"):
            h.update(fn.encode("utf-8"))
            with open(os.path.join(pkg, fn), "rb") as f:
                h.update(f.read())
    return h.hexdigest()[:16]


def load_audit_cached(root: str, relpath: str, cache_dir: str,
                      version: str):
    """Per-module parse + audit through the fact cache.

    Key = sha256(relpath + source) + analyzer version: an unchanged
    module re-loads its pickled ModuleAudit (AST, accesses, calls,
    locks, per-module findings — everything the whole-program passes
    consume) instead of re-walking; any source or analyzer change
    misses and re-parses. Corrupt or unreadable entries fall back to a
    fresh parse — the cache can slow a scan down, never change it.
    Returns None for unparseable modules (same contract as
    ``load_module``)."""
    from tpu_cc_manager.analysis import rules

    with open(os.path.join(root, relpath), "r", encoding="utf-8") as f:
        src = f.read()
    digest = hashlib.sha256(
        (relpath + "\0" + src).encode("utf-8")
    ).hexdigest()[:32]
    path = os.path.join(cache_dir, f"{digest}-{version}.pkl")
    try:
        with open(path, "rb") as f:
            audit = pickle.load(f)
        if getattr(audit, "module", None) is not None \
                and audit.module.relpath == relpath:
            return audit
    except Exception:
        # ccaudit: allow-swallow(cache miss / corrupt / stale-format entry: the contract is fall back to a fresh parse — a cache can slow a scan down, never break it)
        pass
    try:
        mod = Module(relpath, src)
    except SyntaxError:
        return None
    audit = rules.audit_module(mod)
    try:
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            pickle.dump(audit, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)  # atomic: concurrent scans never see halves
    except Exception:
        # ccaudit: allow-swallow(cache write failure — read-only checkout, full disk: the scan already has the fresh audit in hand and proceeds uncached)
        pass
    return audit


def load_module(root: str, relpath: str) -> Optional[Module]:
    with open(os.path.join(root, relpath), "r", encoding="utf-8") as f:
        src = f.read()
    try:
        return Module(relpath, src)
    except SyntaxError:
        # compileall (the other half of `make lint`) owns syntax errors;
        # double-reporting them here would just be noise
        return None


# --------------------------------------------------------------------- runs


def analyze_modules(
    modules: Sequence[Module], call_depth: Optional[int] = None,
) -> List[Finding]:
    """Run every rule over already-parsed modules (the seam the fixture
    tests use: build Modules from inline snippets, skip the filesystem).

    v4 pipeline: parse → per-module rules → whole-program call graph →
    thread roots → transitive lock-order/blocking + lockset race pass →
    asyncflow (await-atomicity, loop-affinity, task-lifecycle,
    async-exception) → findings (the baseline gate is the caller's job).
    """
    findings, _ = _analyze_modules(modules, call_depth)
    return findings


def _analyze_modules(
    modules: Sequence[Module], call_depth: Optional[int] = None,
    audits: Optional[list] = None,
) -> Tuple[List[Finding], list]:
    """analyze_modules plus the per-module audits — analyze_paths
    feeds the audits' metric-declaration registry to the slo
    cross-check (analysis/slo.py). ``audits`` short-circuits the
    per-module stage with already-computed (possibly cache-loaded)
    ModuleAudits, aligned 1:1 with ``modules`` — the whole-program
    passes below always run fresh over the full fact set, so a cache
    hit can never change what a scan reports."""
    from tpu_cc_manager.analysis import (
        asyncflow,
        callgraph,
        dataflow,
        jitflow,
        lockgraph,
        lockset,
        resourceflow,
        rules,
        threads,
    )

    findings: List[Finding] = []
    if audits is None:
        audits = [rules.audit_module(mod) for mod in modules]
    for result in audits:
        findings.extend(result.findings)
    depth = callgraph.DEPTH_LIMIT if call_depth is None else call_depth
    graph = callgraph.build(audits, depth)
    sink_summaries = dataflow.collect_sink_summaries(audits, graph)
    for mod, audit in zip(modules, audits):
        findings.extend(
            dataflow.protocol_findings(mod, audit, graph, sink_summaries)
        )
    findings.extend(lockgraph.order_findings(audits, graph))
    findings.extend(callgraph.blocking_findings(audits, graph))
    roots = threads.infer_roots(audits, graph)
    async_lock_quals = frozenset(
        q for a in audits for q in a.async_lock_quals
    )
    findings.extend(
        lockset.race_findings(audits, graph, roots, async_lock_quals)
    )
    findings.extend(asyncflow.async_findings(audits, graph, roots))
    findings.extend(jitflow.jitflow_findings(audits, graph, roots))
    findings.extend(resourceflow.resource_findings(audits, graph))
    findings.extend(rules.metric_findings(audits))
    findings.extend(rules.liveness_findings(audits))
    findings.extend(rules.direct_write_findings(modules))
    findings.extend(rules.planner_bypass_findings(modules))
    findings.extend(rules.shard_bypass_findings(modules))
    findings.extend(rules.region_bypass_findings(modules))
    findings.extend(rules.blocking_in_async_findings(modules))
    findings.extend(rules.poll_in_watch_path_findings(modules))
    return sorted(findings), audits


def analyze_paths(
    root: Optional[str] = None,
    targets: Sequence[str] = DEFAULT_TARGETS,
    with_manifests: Optional[bool] = None,
    call_depth: Optional[int] = None,
    subset: bool = False,
    cache: bool = False,
) -> List[Finding]:
    """Full repo scan: the AST rules over ``targets`` plus — when scanning
    the default surface (or when ``with_manifests`` forces it) — the
    code↔manifest cross-check over the deploy/scenario trees.

    ``subset=True`` (the CLI's ``--files`` mode) marks ``targets`` as a
    changed-files slice — but the ANALYSIS still runs over the full
    default surface, and only the REPORT is restricted to the slice.
    Whole-program facts (caller-held locksets, thread roots,
    loop-confinement, settle closures) computed over a slice would
    diverge from the merge gate's: a write is unguarded or a function
    mixed-context only relative to every caller, and most callers live
    outside any given diff. Filtering the report instead guarantees a
    subset run flags exactly the full run's findings for those files.
    Only the manifest/slo cross-checks are skipped — their findings
    land on manifest files a Python slice can never contain.

    ``cache=True`` (the CLI's ``--cache``) routes the per-module parse
    stage through the content-hash fact cache under
    ``<root>/.ccaudit_cache/`` — only changed modules re-parse, while
    the whole-program passes still run fresh over every module's
    facts, so a cached scan reports exactly what an uncached one
    would."""
    root = root or repo_root()
    report_only: Optional[Set[str]] = None
    if subset:
        report_only = set(iter_python_files(root, targets))
        targets = DEFAULT_TARGETS
        with_manifests = False
    if with_manifests is None:
        with_manifests = tuple(targets) == DEFAULT_TARGETS
    modules = []
    audits_in: Optional[list] = None
    if cache:
        cache_dir = os.path.join(root, CACHE_DIR_NAME)
        os.makedirs(cache_dir, exist_ok=True)
        version = analyzer_version_hash()
        audits_in = []
        for rel in iter_python_files(root, targets):
            audit = load_audit_cached(root, rel, cache_dir, version)
            if audit is not None:
                modules.append(audit.module)
                audits_in.append(audit)
    else:
        for rel in iter_python_files(root, targets):
            mod = load_module(root, rel)
            if mod is not None:
                modules.append(mod)
    findings, audits = _analyze_modules(modules, call_depth, audits_in)
    if with_manifests:
        from tpu_cc_manager.analysis import manifests, slo

        findings.extend(manifests.manifest_findings(root))
        # the slo cross-check rides the manifest surface: schema
        # (manifest-drift) + metric liveness against the scan's
        # declaration registry (the metric-name rule, extended)
        declared = {
            name for a in audits for name in a.metric_decls
        }
        findings.extend(slo.slo_findings(root, declared))
    if report_only is not None:
        findings = [f for f in findings if f.file in report_only]
    return sorted(findings)


def analyze_source(
    source: str, relpath: str = "snippet.py",
    call_depth: Optional[int] = None,
) -> List[Finding]:
    """Analyze one in-memory module — the unit-test entry point."""
    return analyze_modules([Module(relpath, source)], call_depth)
