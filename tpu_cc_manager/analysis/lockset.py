"""ccaudit static lockset race analyzer (v3).

The Eraser discipline (PAPERS.md: lockset/happens-before detectors),
transplanted from a dynamic tool to a static pass over the call graph:
every location shared between threads must have a lock that is held on
every write. Statically:

- **locations** are ``self.``-attributes (keyed by module + class +
  name, including accesses through ``outer = self`` closure aliases)
  and mutable module globals;
- a location is **shared** when its accesses span more than one thread
  context — two different roots from ``threads.infer_roots``, a root
  plus main-thread code, or a single *self-concurrent* root (executor
  workers, per-request handlers, loop-spawned threads);
- the **lockset of an access** is the set of lock quals held lexically
  at the site; the guard discipline of a location is the intersection
  of its write locksets (the lattice: ⊤ = all locks before the first
  write, ∩ at each write, ⊥ = ∅ = racy).

A shared location **written** with an empty lockset, or whose write
locksets have an empty intersection (two writers under *different*
locks), is a ``race-lockset`` finding at the write site.

Recognized non-races (no finding):

- **reads-only sharing** — locations never written outside init;
- **init-before-spawn** — writes in ``__init__``/module top level, and
  writes lexically before the first ``.start()`` in a function that
  spawns a thread;
- **consistently guarded writes** with unguarded reads: under the GIL a
  single attribute load is atomic, and flagging every bare read would
  drown the write-side signal (the deliberate deviation from Eraser —
  docs/analysis.md §v3 walks through an example).

Deliberate benign races (monotonic latches, best-effort counters whose
loss is acceptable) carry ``# ccaudit: allow-race-lockset(reason)`` on
the write line.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Sequence, Set, Tuple

from tpu_cc_manager.analysis.callgraph import CallGraph
from tpu_cc_manager.analysis.core import Finding
from tpu_cc_manager.analysis.rules import AccessSite, ModuleAudit
from tpu_cc_manager.analysis.threads import MAIN, ThreadRoot, contexts

RULE = "race-lockset"

#: (module dotted, "attr"/"global", class-or-"", name)
LocationKey = Tuple[str, str, str, str]


def _location_key(mod: str, access: AccessSite) -> LocationKey:
    if access.key[0] == "attr":
        return (mod, "attr", access.key[1], access.key[2])
    return (mod, "global", "", access.key[1])


def _display(key: LocationKey) -> str:
    mod_base = key[0].rsplit(".", 1)[-1]
    if key[1] == "attr":
        return f"{mod_base}.{key[2]}.{key[3]}"
    return f"{mod_base}.{key[3]}"


def _root_names(ctx: Set[str]) -> str:
    short = sorted(
        q.rsplit(".", 1)[-1] if q != MAIN else "main" for q in ctx
    )
    return ", ".join(short[:4]) + ("…" if len(short) > 4 else "")


def _caller_held(
    audits: Sequence[ModuleAudit],
    graph: CallGraph,
    roots: Dict[str, ThreadRoot],
) -> Dict[str, FrozenSet[str]]:
    """Locks provably held on EVERY resolved call path into a function
    (the ``_locked``-suffix convention: ``_note_outcome_locked`` is
    guarded by its callers' ``with self._active_lock:``). Computed as a
    depth-bounded intersection fixpoint: held(F) = ⋂ over call sites of
    (locks lexically held at the site ∪ held(caller)).

    A thread ROOT is pinned to ∅ regardless of its call sites: the
    Thread-spawn entry path holds nothing, so a root that also happens
    to be called under a lock (``scan_once`` spawned AND called from
    the run loop) must not have its writes laundered as guarded."""
    call_sites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
    for audit in audits:
        for fn in audit.functions:
            for call in fn.calls:
                callee = graph.resolve_call(audit, fn, call)
                if callee is not None and callee not in roots:
                    call_sites.setdefault(callee, []).append(
                        (fn.qual, call.held_locks)
                    )
    held: Dict[str, FrozenSet[str]] = {}
    for _ in range(graph.depth):
        changed = False
        for callee, sites in call_sites.items():
            acc: FrozenSet[str] = frozenset()
            for i, (caller, locks) in enumerate(sites):
                path = locks | held.get(caller, frozenset())
                acc = path if i == 0 else (acc & path)
            if acc != held.get(callee, frozenset()):
                held[callee] = acc
                changed = True
        if not changed:
            break
    return held


#: Public seam for the v4 asyncflow pass (``analysis/asyncflow.py``):
#: the await-atomicity family widens coroutine locksets with the same
#: caller-held ⋂-fixpoint, so the ``_locked``-suffix convention means
#: one thing across both concurrency models.
caller_held_locks = _caller_held


def race_findings(
    audits: Sequence[ModuleAudit],
    graph: CallGraph,
    roots: Dict[str, ThreadRoot],
    async_lock_quals: FrozenSet[str] = frozenset(),
) -> List[Finding]:
    fn_ctx = contexts(graph, roots)
    caller_held = _caller_held(audits, graph, roots)

    # gather all accesses per location, widening each access's lockset
    # with the locks every caller provably holds around its function
    sites: Dict[LocationKey, List[AccessSite]] = {}
    for audit in audits:
        for fn in audit.functions:
            inherited = caller_held.get(fn.qual, frozenset())
            for a in fn.accesses:
                if inherited:
                    a = AccessSite(
                        key=a.key, kind=a.kind,
                        locks=a.locks | inherited, init=a.init,
                        fn_qual=a.fn_qual, file=a.file, line=a.line,
                        text=a.text, suppressed=a.suppressed,
                        prespawn=a.prespawn,
                    )
                sites.setdefault(_location_key(audit.dotted, a), []).append(a)

    def _prespawn_safe(a: AccessSite) -> bool:
        """A pre-``.start()`` write happens-before the spawned thread —
        but only shields the location when the spawning function itself
        runs in one non-self-concurrent context (two concurrent
        ``respawn()`` calls still tear the write)."""
        if not a.prespawn:
            return False
        ctx = fn_ctx.get(a.fn_qual) or {MAIN}
        if len(ctx) > 1:
            return False
        return not any(roots[r].self_concurrent for r in ctx if r in roots)

    findings: List[Finding] = []
    for key in sorted(sites):
        # init accesses happen-before every spawn: they neither fire
        # nor establish a thread context; qualifying prespawn writes
        # get the same treatment
        accesses = [
            a for a in sites[key] if not a.init and not _prespawn_safe(a)
        ]
        if not accesses:
            continue
        ctx_of: List[Set[str]] = [
            fn_ctx.get(a.fn_qual) or {MAIN} for a in accesses
        ]
        all_ctx: Set[str] = set().union(*ctx_of)
        if len(all_ctx) < 2 and not any(
            roots[r].self_concurrent for r in all_ctx if r in roots
        ):
            continue  # single-threaded location
        # a pragma'd write asserts an out-of-band happens-before (e.g.
        # prime() before the watcher thread starts) — it neither fires
        # nor drags its context into the race computation
        writes = [
            (a, c) for a, c in zip(accesses, ctx_of)
            if a.kind == "write" and not a.suppressed
        ]
        if not writes:
            continue  # reads-only sharing (plus init writes): fine
        # fire only on the lost-update shape: writes racing writes.
        # A single writer thread with unguarded readers is tolerated —
        # under the GIL a one-slot store/load is atomic, and flagging
        # every bare read would drown the signal (docs/analysis.md §v3)
        write_ctx: Set[str] = set().union(*(c for _, c in writes))
        write_self_concurrent = any(
            roots[r].self_concurrent for r in write_ctx if r in roots
        )
        if len(write_ctx) < 2 and not write_self_concurrent:
            continue
        # the lockset lattice: ∩ of write locksets. An asyncio lock
        # excludes coroutines on ONE loop, not threads — so v4 passes
        # the async-lock quals in and they are discounted here: a write
        # "guarded" only by an asyncio.Lock is unguarded thread-wise.
        write_locksets: List[FrozenSet[str]] = [
            a.locks - async_lock_quals for a, _ in writes
        ]
        common: FrozenSet[str] = write_locksets[0]
        for ls in write_locksets[1:]:
            common = common & ls
        consistent = bool(common)
        for (access, _), eff in zip(writes, write_locksets):
            if eff and consistent:
                continue
            if eff:
                others = sorted(
                    set().union(*(ls for ls in write_locksets)) - eff
                )
                message = (
                    f"{_display(key)} is written under "
                    f"{{{', '.join(sorted(eff))}}} here but "
                    f"under {{{', '.join(others)}}} elsewhere — the write "
                    "locksets share no common lock, so the location is "
                    "unprotected (shared across: "
                    f"{_root_names(all_ctx)})"
                )
            else:
                message = (
                    f"{_display(key)} is written with no lock held while "
                    f"shared across thread contexts "
                    f"({_root_names(all_ctx)}) — a lost update or torn "
                    "read-modify-write at fleet scale; guard every write "
                    "with one lock, or annotate "
                    "`# ccaudit: allow-race-lockset(reason)`"
                )
            findings.append(
                Finding(
                    file=access.file,
                    line=access.line,
                    rule=RULE,
                    message=message,
                    text=access.text,
                )
            )
    return sorted(set(findings))
