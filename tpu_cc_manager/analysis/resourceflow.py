"""ccaudit resource & overload-discipline pass (v6 "resourceflow").

ROADMAP item 3 (overload discipline) starts from an admission the aio
core's own docs make: past the connection budget, writers queue without
bound (docs/io.md). The paper's agent is fail-secure only if every
drain, flip, and publish path degrades *deliberately* under saturation
— and v1–v5 see locks, protocol flows, threads, the event loop and the
JAX dispatch surface, but are blind to the hazard class that dominates
a saturated control plane: unbounded backlogs, blocking calls with no
deadline, retry storms without backoff or jitter, leaked sockets and
executors, and waits a SIGTERM cannot interrupt. This module teaches
the analyzer those disciplines — five rule families over the same
per-function records and call graph the thread/async/jit passes consume
(docs/analysis.md §v6 has the full contract):

``unbounded-queue``
    A ``queue.Queue``/``asyncio.Queue`` family constructor without a
    positive ``maxsize``, a ``queue.SimpleQueue`` (no bound exists), or
    a cross-context ``collections.deque`` (stored to ``self.`` or a
    module global) without ``maxlen``, anywhere on the package surface.
    An unbounded queue turns overload into latency and memory growth
    instead of an honest error; the aio writer backlog was the seeded
    true positive (now bounded behind ``TPU_CC_KUBE_QUEUE`` with
    ``tpu_cc_kube_queue_rejected_total`` accounting). Function-local
    scratch deques are exempt — they cannot outlive one call. **Error**
    severity. Pragma: ``allow-unbounded-queue(reason)``.

``missing-deadline``
    A BOUNDED/UNBOUNDED timeout lattice over the reconcile/scan/flip
    call-graph closure (widened with the aio/batch/client I/O core —
    that IS the reconcile I/O surface): every blocking sink that takes
    a deadline — ``Future.result``, ``concurrent.futures.wait``,
    ``subprocess.run``/``communicate``, ``requests.*``,
    ``select.select``, and awaited stream reads / semaphore acquires /
    queue gets — must receive one on every caller path. Recognizers:
    ``asyncio.wait_for`` wrapping, deadline-clamp arithmetic
    (``max(0.1, deadline - time.monotonic())`` stays BOUNDED through
    ``min``/``max``/``-``), and timeout-*forwarding* parameters, which
    are resolved through a caller-path ⋂-fixpoint: a parameter is
    BOUNDED only if its default is a bounded constant or every resolved
    call site passes a bounded value (transitively through the callers'
    own parameters). Pragma: ``allow-missing-deadline(reason)``.

``retry-discipline``
    A retry loop — a ``for``/``while`` whose ``try`` does I/O and whose
    ``except`` lets the loop go around again — must show all three
    legs: an attempt/deadline **cap** (finite iterator, an
    attempt-counter or deadline compare, or a stop-governed wait),
    **backoff growth** (``*=``/``2 ** n`` shapes, or a call whose
    call-graph closure shows them), and **jitter** (``random.*`` or a
    jitter-named helper, same transitive summary). Any missing leg
    fires, naming the legs. Two-attempt replay loops (``for attempt in
    (0, 1)``) are the exactly-once replay shape, not congestion
    control, and are exempt. Pragma: ``allow-retry-discipline(reason)``.

``resource-leak``
    Path-sensitive acquire/release over sockets, files, executors,
    tempfiles and subprocesses: an acquisition bound to a local must
    reach a close-family sink (``close``/``shutdown``/``cleanup``/
    ``terminate``/``kill``/``aclose``) under ``try/finally``, be used
    as a context manager, or visibly transfer ownership (returned,
    yielded, stored, or passed to another call). A close reachable only
    on the straight-line path — not in a ``finally`` — fires the
    exception-path variant. ``self.``-attribute acquisitions must have
    SOME close site for that attribute in the module. Pragma:
    ``allow-resource-leak(reason)``.

``stop-aware-wait``
    Blocking waits on controller/reconcile threads must ride a
    stop/shutdown-interruptible primitive — the ``_stop``-Event
    convention (``self._stop.wait(t)``, never ``time.sleep(t)``) — so
    SIGTERM never hangs a flip. A wait on a non-stop event needs a
    bounded timeout, and inside a loop the loop must consult the stop
    signal. **Error** severity when the wait sits in a loop (the
    loop-wedging shape); warning otherwise. ``time.sleep``-in-loop
    sites inside the poll-path modules stay owned by the existing
    ``poll-in-watch-path`` rule (no double report). Pragma:
    ``allow-stop-aware-wait(reason)``.

All five ids take ``# ccaudit: allow-<rule>(reason)`` pragmas; the
baseline ratchet, SARIF output and ``--files``/``--cache`` modes treat
them exactly like every earlier family.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import (
    Dict,
    FrozenSet,
    Iterator,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from tpu_cc_manager.analysis.callgraph import CallGraph
from tpu_cc_manager.analysis.core import Finding, Module, resolve_dotted
from tpu_cc_manager.analysis.rules import (
    FnAudit,
    ModuleAudit,
    POLL_PATH_MODULES,
)

QUEUE_RULE = "unbounded-queue"
DEADLINE_RULE = "missing-deadline"
RETRY_RULE = "retry-discipline"
LEAK_RULE = "resource-leak"
STOP_RULE = "stop-aware-wait"

#: every v6 family, in contract order (bench stamps this count so the
#: smoke job can assert the pass actually ran)
RESOURCEFLOW_RULES = (
    QUEUE_RULE, DEADLINE_RULE, RETRY_RULE, LEAK_RULE, STOP_RULE,
)

#: module prefixes exempt from the v6 families: benches and scripts are
#: one-shot CLIs whose backlog is their argv, simlab drives wall-clock
#: scenarios on purpose, and the analyzer itself is a batch tool with
#: no controller thread to wedge.
_EXEMPT_PREFIXES = (
    "bench.py", "scripts/", "tpu_cc_manager/simlab/",
    "tpu_cc_manager/analysis/",
)

#: controller/reconcile-thread modules — the threads SIGTERM must be
#: able to interrupt (the stop-aware-wait surface). The k8s transport
#: and device layers are deliberately absent: their waits are bounded
#: by per-call read timeouts and stop-awareness lives one layer up.
STOP_SURFACE_MODULES = frozenset({
    "tpu_cc_manager/agent.py",
    "tpu_cc_manager/fleet.py",
    "tpu_cc_manager/policy.py",
    "tpu_cc_manager/engine.py",
    "tpu_cc_manager/flipexec.py",
    "tpu_cc_manager/drain.py",
    "tpu_cc_manager/rollout.py",
    "tpu_cc_manager/watch.py",
    "tpu_cc_manager/leader.py",
    "tpu_cc_manager/federation.py",
    "tpu_cc_manager/shard.py",
    "tpu_cc_manager/slice_coord.py",
    "tpu_cc_manager/tsring.py",
    "tpu_cc_manager/fleetobs.py",
    "tpu_cc_manager/webhook.py",
    "tpu_cc_manager/profiler.py",
})

#: the I/O core: every function here is on the reconcile closure by
#: definition — the controllers' blocking calls bottom out in these
#: modules whether or not the nominal call graph can see through an
#: untyped ``kube`` parameter.
IO_CORE_MODULES = frozenset({
    "tpu_cc_manager/k8s/aio.py",
    "tpu_cc_manager/k8s/aio_bridge.py",
    "tpu_cc_manager/k8s/batch.py",
    "tpu_cc_manager/k8s/client.py",
})

#: function names that root the missing-deadline closure: the
#: controllers' reconcile/scan bodies and the flip executor's entry
_DEADLINE_ROOT_NAMES = frozenset({
    "reconcile", "scan_once", "_scan", "run_flips",
})

#: receiver names that carry the stop/shutdown convention — waiting on
#: one of these IS the interruptible wait (``_wake`` qualifies because
#: ``stop()`` pulses it alongside ``_stop``; fleet.py's run loop is the
#: charter example)
_STOP_NAME_RE = re.compile(
    r"(stop|shutdown|halt|quit|exit|term|abort|wake|cancel)", re.I,
)

#: timeout argument names that read as deadline clamps ("how much of my
#: budget is left"), accepted on non-stop waits
_REMAINING_NAME_RE = re.compile(
    r"(remaining|deadline|budget|left|until)", re.I,
)

#: queue-shaped receiver names for blocking ``.get()`` recognition
_QUEUE_NAME_RE = re.compile(r"(queue|mailbox|inbox|_q$|^q$)", re.I)

#: names whose appearance in a loop's compare reads as an attempt or
#: deadline cap
_CAP_NAME_RE = re.compile(
    r"(attempt|tr(y|ies)|retr|count|budget|deadline|until|remaining|"
    r"elapsed|failure)", re.I,
)

#: close-family method names — the transitive release sinks
_CLOSE_ATTRS = frozenset({
    "close", "shutdown", "cleanup", "terminate", "kill", "aclose",
})

#: I/O-verb attribute prefixes for the retry-loop sink gate
_IO_ATTR_PREFIXES = (
    "get_", "list_", "patch_", "replace_", "create_", "delete_",
    "set_", "publish", "flush", "send", "recv", "read", "write",
    "connect", "dial", "request", "_request", "fetch", "watch",
    "relist", "_relist", "put_", "post",
)

#: dotted prefixes that always count as I/O
_IO_DOTTED_PREFIXES = (
    "requests.", "urllib.", "socket.", "subprocess.", "http.",
)

#: acquisition constructors for the resource-leak family, by resolved
#: dotted path or terminal name
_ACQUIRE_RESOLVED = {
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "tempfile.NamedTemporaryFile": "tempfile",
    "tempfile.TemporaryFile": "tempfile",
    "tempfile.TemporaryDirectory": "tempdir",
    "subprocess.Popen": "subprocess",
}
_ACQUIRE_TERMINALS = {
    "ThreadPoolExecutor": "executor",
    "ProcessPoolExecutor": "executor",
}


def _is_exempt(relpath: str) -> bool:
    return any(
        relpath == p or relpath.startswith(p) for p in _EXEMPT_PREFIXES
    )


def _finding(
    mod: Module, rule: str, line: int, message: str, severity: str,
) -> Finding:
    return Finding(
        file=mod.relpath,
        line=line,
        rule=rule,
        message=message,
        text=mod.line_text(line),
        severity=severity,
    )


def _terminal(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _ordered_body(fn: ast.AST) -> Iterator[ast.AST]:
    """Preorder, source-ordered nodes lexically inside ``fn``, not
    descending into nested defs (a nested def's body runs when *it* is
    called, not where it is defined)."""
    for child in ast.iter_child_nodes(fn):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from _ordered_body(child)


def _names_in(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
    return out


# ----------------------------------------------------------- entry point


def resource_findings(
    audits: Sequence[ModuleAudit], graph: CallGraph,
) -> List[Finding]:
    """Run all five v6 families over already-collected audits."""
    findings: List[Finding] = []
    findings.extend(_queue_findings(audits))
    findings.extend(_stop_findings(audits))
    findings.extend(_leak_findings(audits))
    findings.extend(_retry_findings(audits, graph))
    findings.extend(_deadline_findings(audits, graph))
    return sorted(set(findings))


# ----------------------------------------------- family 1: unbounded-queue


def _queue_kind(
    call: ast.Call, imports: Dict[str, str],
) -> Optional[str]:
    """Classify a constructor call: "queue" (maxsize semantics),
    "simple" (never boundable), or "deque" (maxlen semantics)."""
    resolved = resolve_dotted(call.func, imports) or ""
    if resolved == "queue.SimpleQueue":
        return "simple"
    if resolved in (
        "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
        "asyncio.Queue", "asyncio.LifoQueue", "asyncio.PriorityQueue",
        "multiprocessing.Queue",
    ):
        return "queue"
    if resolved == "collections.deque":
        return "deque"
    return None


def _queue_is_bounded(call: ast.Call, kind: str) -> bool:
    if kind == "simple":
        return False
    if kind == "deque":
        # deque(iterable, maxlen) — the bound is the SECOND positional
        # or the maxlen keyword, and an explicit None is no bound
        if len(call.args) >= 2:
            return True
        for kw in call.keywords:
            if kw.arg == "maxlen":
                return not (isinstance(kw.value, ast.Constant)
                            and kw.value.value is None)
        return False
    # Queue family: maxsize is the first positional or keyword;
    # missing, zero, negative, or None all mean unbounded
    bound: Optional[ast.AST] = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "maxsize":
            bound = kw.value
    if bound is None:
        return False
    if isinstance(bound, ast.Constant):
        return isinstance(bound.value, (int, float)) and bound.value > 0
    return True  # a computed bound is a bound


def _queue_findings(audits: Sequence[ModuleAudit]) -> List[Finding]:
    out: List[Finding] = []
    for audit in audits:
        mod = audit.module
        if _is_exempt(mod.relpath):
            continue
        if "Queue" not in mod.source and "deque" not in mod.source:
            continue
        _scan_queue_stmts(mod, audit.imports, mod.tree.body, "module", out)
    return out


def _scan_queue_stmts(
    mod: Module, imports: Dict[str, str], stmts: Sequence[ast.stmt],
    ctx: str, out: List[Finding],
) -> None:
    """Recursive statement walk tracking the binding context: "module"
    and "class" bindings (and any ``self.``-attribute store) are
    cross-context containers; a bare local deque is scratch."""
    for stmt in stmts:
        if isinstance(stmt, ast.ClassDef):
            _scan_queue_stmts(mod, imports, stmt.body, "class", out)
            continue
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_queue_stmts(mod, imports, stmt.body, "fn", out)
            continue
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                _scan_queue_stmts(mod, imports, [child], ctx, out)
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, ast.AnnAssign):
            targets = [stmt.target]
        for call in [
            n for n in ast.walk(stmt) if isinstance(n, ast.Call)
        ]:
            kind = _queue_kind(call, imports)
            if kind is None or _queue_is_bounded(call, kind):
                continue
            if kind == "deque":
                # only cross-context deques: stored to self./a class or
                # module binding. Function-local scratch is exempt.
                value_of_stmt = getattr(stmt, "value", None)
                cross = value_of_stmt is call and (
                    any(isinstance(t, ast.Attribute) for t in targets)
                    or (ctx in ("module", "class")
                        and any(isinstance(t, ast.Name) for t in targets))
                )
                if not cross:
                    continue
            if mod.suppressed(QUEUE_RULE, call.lineno):
                continue
            what = ("queue.SimpleQueue has no bound at all — use "
                    "queue.Queue(maxsize=...)" if kind == "simple" else
                    "no maxlen" if kind == "deque" else
                    "no positive maxsize")
            out.append(_finding(
                mod, QUEUE_RULE, call.lineno,
                f"unbounded queue constructed here ({what}): under "
                "overload this backlog grows without limit, turning "
                "saturation into memory growth and unbounded latency "
                "instead of an honest rejection — bound it (the aio "
                "writer backlog rides TPU_CC_KUBE_QUEUE with "
                "tpu_cc_kube_queue_rejected_total accounting) or carry "
                "allow-unbounded-queue(reason)",
                severity="error",
            ))
        # recurse into compound statements (loops/ifs/try/with bodies)
        for body_attr in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(stmt, body_attr, None)
            if not sub:
                continue
            if body_attr == "handlers":
                for h in sub:
                    _scan_queue_stmts(mod, imports, h.body, ctx, out)
            elif isinstance(sub, list) and sub and isinstance(
                    sub[0], ast.stmt):
                _scan_queue_stmts(mod, imports, sub, ctx, out)


# --------------------------------------------- family 5: stop-aware-wait


@dataclass
class _WaitCtx:
    in_loop: bool = False
    #: While tests of every enclosing loop (stop checks live there)
    loop_tests: Tuple[ast.AST, ...] = ()


def _stop_findings(audits: Sequence[ModuleAudit]) -> List[Finding]:
    out: List[Finding] = []
    for audit in audits:
        mod = audit.module
        if mod.relpath not in STOP_SURFACE_MODULES:
            continue
        for fn in audit.functions:
            if fn.node is None or fn.is_async:
                continue
            _walk_stop(mod, audit.imports, fn.node, _WaitCtx(), out)
    return out


def _loops_consult_stop(ctx: _WaitCtx) -> bool:
    return any(
        any(_STOP_NAME_RE.search(n) for n in _names_in(t))
        for t in ctx.loop_tests
    )


def _walk_stop(
    mod: Module, imports: Dict[str, str], node: ast.AST, ctx: _WaitCtx,
    out: List[Finding],
) -> None:
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        if isinstance(child, ast.While):
            sub = _WaitCtx(True, ctx.loop_tests + (child.test,))
        elif isinstance(child, ast.For):
            sub = _WaitCtx(True, ctx.loop_tests)
        else:
            sub = ctx
        if isinstance(child, ast.Call):
            _check_wait_call(mod, imports, child, ctx, out)
        _walk_stop(mod, imports, child, sub, out)


def _check_wait_call(
    mod: Module, imports: Dict[str, str], call: ast.Call, ctx: _WaitCtx,
    out: List[Finding],
) -> None:
    severity = "error" if ctx.in_loop else "warning"
    resolved = resolve_dotted(call.func, imports) or ""
    line = call.lineno
    if resolved == "time.sleep":
        if ctx.in_loop and mod.relpath in POLL_PATH_MODULES:
            return  # owned by poll-in-watch-path (no double report)
        if mod.suppressed(STOP_RULE, line):
            return
        out.append(_finding(
            mod, STOP_RULE, line,
            "time.sleep on a controller thread is not stop-"
            "interruptible: SIGTERM waits out the full sleep"
            + (" on every loop turn" if ctx.in_loop else "")
            + " — ride the stop event (`self._stop.wait(t)` returns "
            "early on shutdown) or carry "
            "allow-stop-aware-wait(reason)",
            severity=severity,
        ))
        return
    if not isinstance(call.func, ast.Attribute):
        return
    attr = call.func.attr
    recv = _terminal(call.func.value) or ""
    if attr == "wait":
        if _STOP_NAME_RE.search(recv):
            return  # the convention itself
        timeout: Optional[ast.AST] = (
            call.args[0] if call.args else None
        )
        for kw in call.keywords:
            if kw.arg == "timeout":
                timeout = kw.value
        if timeout is None or (isinstance(timeout, ast.Constant)
                               and timeout.value is None):
            if mod.suppressed(STOP_RULE, line):
                return
            out.append(_finding(
                mod, STOP_RULE, line,
                f"`{recv}.wait()` with no timeout on a controller "
                "thread: nothing interrupts it on shutdown — wait on "
                "the stop event, or give it a timeout inside a "
                "stop-checking loop",
                severity=severity,
            ))
            return
        if ctx.in_loop and not _loops_consult_stop(ctx):
            t_names = _names_in(timeout)
            if any(_REMAINING_NAME_RE.search(n) for n in t_names):
                return  # deadline-clamped wait: bounded overall
            if mod.suppressed(STOP_RULE, line):
                return
            out.append(_finding(
                mod, STOP_RULE, line,
                f"loop waits on `{recv}` without consulting the stop "
                "signal: each turn re-arms the wait, so SIGTERM never "
                "lands — gate the loop on `self._stop.is_set()` (or "
                "wait on the stop event directly)",
                severity="error",
            ))
        return
    if attr == "get" and not call.args and _QUEUE_NAME_RE.search(recv):
        if any(kw.arg == "timeout" for kw in call.keywords):
            return
        if mod.suppressed(STOP_RULE, line):
            return
        out.append(_finding(
            mod, STOP_RULE, line,
            f"blocking `{recv}.get()` with no timeout on a controller "
            "thread: an empty queue parks it past any shutdown — use "
            "`get(timeout=...)` in a stop-checking loop",
            severity=severity,
        ))


# ------------------------------------------------ family 4: resource-leak


@dataclass
class _Acquisition:
    name: str
    kind: str
    line: int


def _acquire_kind(
    call: ast.Call, imports: Dict[str, str],
) -> Optional[str]:
    resolved = resolve_dotted(call.func, imports)
    if resolved in _ACQUIRE_RESOLVED:
        return _ACQUIRE_RESOLVED[resolved]
    term = _terminal(call.func)
    if term in _ACQUIRE_TERMINALS:
        return _ACQUIRE_TERMINALS[term]
    if isinstance(call.func, ast.Name) and call.func.id == "open" \
            and resolved in (None, "open"):
        # the builtin resolves to its own bare name; an import-shadowed
        # `open` (gzip.open…) resolves dotted and is out of scope
        return "file"
    return None


def _leak_findings(audits: Sequence[ModuleAudit]) -> List[Finding]:
    out: List[Finding] = []
    for audit in audits:
        mod = audit.module
        if _is_exempt(mod.relpath):
            continue
        #: attr name -> acquisition line, for the module-level sweep
        attr_acquires: List[Tuple[str, int]] = []
        for fn in audit.functions:
            if fn.node is None:
                continue
            _leak_scan_fn(mod, audit.imports, fn, attr_acquires, out)
        if attr_acquires:
            closed = _module_closed_attrs(mod)
            for attr, line in attr_acquires:
                if attr in closed or mod.suppressed(LEAK_RULE, line):
                    continue
                out.append(_finding(
                    mod, LEAK_RULE, line,
                    f"`self.{attr}` acquires a resource but nothing in "
                    "this module ever closes it (no close/shutdown/"
                    "cleanup call on that attribute): the handle "
                    "outlives every shutdown path — release it in the "
                    "owner's stop()/close(), or carry "
                    "allow-resource-leak(reason)",
                    severity="warning",
                ))
    return out


def _module_closed_attrs(mod: Module) -> Set[str]:
    """Attribute names that SOME site in the module closes or manages:
    ``self.x.close()``, ``with self.x``, or a pure aliasing assignment
    (``pool, self.x = self.x, None`` — the swap-out-then-shutdown
    idiom) that visibly hands the handle to managing code."""
    closed: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) \
                and node.func.attr in _CLOSE_ATTRS \
                and isinstance(node.func.value, ast.Attribute):
            closed.add(node.func.value.attr)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ce = item.context_expr
                if isinstance(ce, ast.Attribute):
                    closed.add(ce.attr)
        elif isinstance(node, ast.Assign):
            vals = (list(node.value.elts)
                    if isinstance(node.value, ast.Tuple)
                    else [node.value])
            if all(isinstance(v, (ast.Attribute, ast.Name, ast.Constant))
                   for v in vals):
                for v in vals:
                    if isinstance(v, ast.Attribute):
                        closed.add(v.attr)
    return closed


def _leak_scan_fn(
    mod: Module, imports: Dict[str, str], fn: FnAudit,
    attr_acquires: List[Tuple[str, int]], out: List[Finding],
) -> None:
    acquisitions: List[_Acquisition] = []
    for node in _ordered_body(fn.node):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        if not isinstance(node.value, ast.Call):
            continue
        kind = _acquire_kind(node.value, imports)
        if kind is None:
            continue
        tgt = node.targets[0]
        if isinstance(tgt, ast.Name):
            acquisitions.append(
                _Acquisition(tgt.id, kind, node.lineno))
        elif isinstance(tgt, ast.Attribute):
            attr_acquires.append((tgt.attr, node.lineno))
    for acq in acquisitions:
        verdict = _local_release_verdict(fn.node, acq)
        if verdict is None or mod.suppressed(LEAK_RULE, acq.line):
            continue
        if verdict == "never":
            msg = (
                f"`{acq.name}` acquires a {acq.kind} that is never "
                "released on any path: wrap it in `with`, or close it "
                "in a try/finally"
            )
        else:
            msg = (
                f"`{acq.name}` ({acq.kind}) is closed only on the "
                "straight-line path — an exception between acquire and "
                "close leaks the handle; move the close into a "
                "`finally` or use a context manager"
            )
        out.append(_finding(mod, LEAK_RULE, acq.line, msg,
                            severity="warning"))


def _local_release_verdict(
    fn_node: ast.AST, acq: _Acquisition,
) -> Optional[str]:
    """None = released/transferred; "never" / "success-only"."""
    name = acq.name
    close_in_finally = False
    close_anywhere = False

    def walk(node: ast.AST, in_finally: bool) -> Optional[bool]:
        nonlocal close_in_finally, close_anywhere
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                # a nested def capturing the handle = escape
                if name in {
                    n.id for n in ast.walk(child)
                    if isinstance(n, ast.Name)
                }:
                    return True
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Name) and ce.id == name:
                        return True
            if isinstance(child, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and child.value is not None and name in {
                        n.id for n in ast.walk(child.value)
                        if isinstance(n, ast.Name)
                    }:
                return True
            if isinstance(child, ast.Assign) and getattr(
                    child, "lineno", 0) > acq.line and name in {
                        n.id for n in ast.walk(child.value)
                        if isinstance(n, ast.Name)
                    }:
                return True  # aliased/stored — ownership transferred
            if isinstance(child, ast.Call):
                f = child.func
                if isinstance(f, ast.Attribute) and isinstance(
                        f.value, ast.Name) and f.value.id == name:
                    if f.attr in _CLOSE_ATTRS:
                        close_anywhere = True
                        if in_finally:
                            close_in_finally = True
                        continue
                else:
                    # the handle passed as an argument = transfer
                    for sub in list(child.args) + [
                        kw.value for kw in child.keywords
                    ]:
                        if any(
                            isinstance(n, ast.Name) and n.id == name
                            for n in ast.walk(sub)
                        ):
                            return True
            if isinstance(child, ast.Try):
                for part in (child.body, child.orelse):
                    for stmt in part:
                        if walk_one(stmt, in_finally):
                            return True
                for h in child.handlers:
                    for stmt in h.body:
                        if walk_one(stmt, in_finally):
                            return True
                for stmt in child.finalbody:
                    if walk_one(stmt, True):
                        return True
                continue
            if walk(child, in_finally):
                return True
        return False

    def walk_one(stmt: ast.AST, in_finally: bool) -> Optional[bool]:
        # apply the same checks to `stmt` itself, then its children
        class _Box(ast.AST):
            _fields = ("x",)
        box = _Box()
        box.x = stmt  # type: ignore[attr-defined]
        return walk(box, in_finally)

    if walk(fn_node, False):
        return None
    if close_in_finally:
        return None
    if close_anywhere:
        return "success-only"
    return "never"


# --------------------------------------------- family 3: retry-discipline


def _retry_findings(
    audits: Sequence[ModuleAudit], graph: CallGraph,
) -> List[Finding]:
    by_qual = {
        fn.qual: (audit, fn)
        for audit in audits for fn in audit.functions
    }
    #: lexical per-function discipline evidence, for the transitive
    #: helper summaries (`jittered_backoff` provides both legs to every
    #: loop whose closure reaches it)
    lexical: Dict[str, Set[str]] = {}
    for audit in audits:
        for fn in audit.functions:
            if fn.node is None:
                continue
            ev: Set[str] = set()
            if "backoff" in fn.name or "jitter" in fn.name:
                ev.add("backoff")
            for node in _ordered_body(fn.node):
                ev |= _leg_evidence(node, audit.imports)
            if ev:
                lexical[fn.qual] = ev
    out: List[Finding] = []
    for audit in audits:
        mod = audit.module
        if _is_exempt(mod.relpath):
            continue
        for fn in audit.functions:
            if fn.node is None:
                continue
            for loop in _loops_of(fn.node):
                res = _check_retry_loop(
                    mod, audit, fn, loop, lexical, by_qual, graph,
                )
                if res is not None:
                    out.append(res)
    return out


def _loops_of(fn_node: ast.AST) -> List[ast.AST]:
    return [
        n for n in _ordered_body(fn_node)
        if isinstance(n, (ast.For, ast.While))
    ]


def _leg_evidence(node: ast.AST, imports: Dict[str, str]) -> Set[str]:
    """Lexical backoff/jitter evidence contributed by one statement."""
    ev: Set[str] = set()
    if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Mult):
        ev.add("backoff")
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Pow):
        ev.add("backoff")
    if isinstance(node, ast.Assign) and isinstance(
            node.targets[0] if node.targets else None, ast.Name):
        tname = node.targets[0].id  # type: ignore[union-attr]
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.BinOp) and isinstance(
                    sub.op, (ast.Mult, ast.Pow)) and tname in {
                        n.id for n in ast.walk(sub)
                        if isinstance(n, ast.Name)
                    }:
                ev.add("backoff")
    if isinstance(node, (ast.Name, ast.Attribute)):
        label = node.id if isinstance(node, ast.Name) else node.attr
        if "jitter" in label.lower():
            ev.add("jitter")
        if "backoff" in label.lower() and isinstance(node, ast.Name):
            pass  # a backoff-NAMED value alone is not growth
    if isinstance(node, ast.Call):
        resolved = resolve_dotted(node.func, imports) or ""
        if resolved.startswith("random."):
            ev.add("jitter")
        term = _terminal(node.func) or ""
        if "jitter" in term.lower():
            ev.add("jitter")
    return ev


def _retry_shape(loop: ast.AST) -> Optional[ast.Try]:
    """The loop's directly-owned retrying Try (its innermost loop is
    ``loop``), or None. A Try retries when some handler neither
    re-raises, returns, nor breaks on its final statement AND the try
    body does I/O."""
    owned: List[ast.Try] = []

    def collect(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.For, ast.While, ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue  # inner loop owns its own tries
            if isinstance(child, ast.Try):
                owned.append(child)
            collect(child)

    collect(loop)
    for t in owned:
        for h in t.handlers:
            if not h.body:
                continue
            last = h.body[-1]
            if isinstance(last, (ast.Raise, ast.Return, ast.Break)):
                continue
            return t
    return None


def _io_in_try(t: ast.Try, imports: Dict[str, str]) -> bool:
    for stmt in t.body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            resolved = resolve_dotted(node.func, imports) or ""
            if resolved.startswith(_IO_DOTTED_PREFIXES):
                return True
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr.startswith(_IO_ATTR_PREFIXES):
                    return True
    return False


def _attempt_iter(it: ast.AST) -> bool:
    """An iterator that counts attempts rather than yielding work
    items: ``range(...)``, a literal sequence, or ``itertools.count``.
    Anything else (a list of nodes, ``/proc`` entries…) makes the loop
    a per-item scan, out of retry-discipline's scope."""
    if isinstance(it, (ast.Tuple, ast.List)):
        return True
    if isinstance(it, ast.Call):
        term = _terminal(it.func)
        return term in ("range", "count", "repeat")
    return False


def _replay_shape(loop: ast.AST) -> bool:
    """``for attempt in (0, 1)`` — the exactly-once replay loop: at
    most two immediate attempts, not congestion control."""
    if not isinstance(loop, ast.For):
        return False
    it = loop.iter
    if isinstance(it, (ast.Tuple, ast.List)) and len(it.elts) <= 2:
        return True
    if isinstance(it, ast.Call) and _terminal(it.func) == "range" \
            and it.args and isinstance(it.args[0], ast.Constant) \
            and isinstance(it.args[0].value, int) \
            and it.args[0].value <= 2 and len(it.args) == 1:
        return True
    return False


def _resolve_simple(
    call: ast.Call, audit: ModuleAudit, fn: FnAudit,
    by_qual: Dict[str, Tuple[ModuleAudit, FnAudit]],
) -> Optional[str]:
    """Nominal call resolution sufficient for discipline summaries:
    bare module/nested names, ``self.m()`` methods, import-folded
    dotted paths."""
    f = call.func
    if isinstance(f, ast.Name):
        for cand in (f"{fn.qual}.{f.id}", f"{audit.dotted}.{f.id}"):
            if cand in by_qual:
                return cand
    resolved = resolve_dotted(f, audit.imports)
    if resolved and resolved in by_qual:
        return resolved
    if isinstance(f, ast.Name):
        return None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "self" and fn.class_path:
        cand = ".".join((audit.dotted,) + fn.class_path + (f.attr,))
        if cand in by_qual:
            return cand
    return None


def _check_retry_loop(
    mod: Module, audit: ModuleAudit, fn: FnAudit, loop: ast.AST,
    lexical: Dict[str, Set[str]],
    by_qual: Dict[str, Tuple[ModuleAudit, FnAudit]],
    graph: CallGraph,
) -> Optional[Finding]:
    if isinstance(loop, ast.For) and not _attempt_iter(loop.iter):
        # a for-over-a-collection never re-attempts the same work: an
        # except that moves on is a per-item skip, not a retry
        return None
    t = _retry_shape(loop)
    if t is None or not _io_in_try(t, audit.imports):
        return None
    if _replay_shape(loop):
        return None
    legs: Set[str] = set()
    # cap: any finite For iterator; a While needs a counter/deadline
    # compare or a stop-governed wait
    if isinstance(loop, ast.For):
        legs.add("cap")
    else:
        probes: List[ast.AST] = [loop.test] + list(loop.body)
        for probe in probes:
            for node in ast.walk(probe):
                if isinstance(node, ast.Compare) and any(
                    _CAP_NAME_RE.search(n) for n in _names_in(node)
                ):
                    legs.add("cap")
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr in ("wait", "is_set") \
                        and _STOP_NAME_RE.search(
                            _terminal(node.func.value) or ""):
                    legs.add("cap")  # stop-governed: the owner bounds it
        if any(_STOP_NAME_RE.search(n) for n in _names_in(loop.test)):
            legs.add("cap")
    # backoff + jitter: lexical in the loop, or via a called helper
    # whose call-graph closure shows the evidence
    body_nodes = list(ast.walk(loop))
    for node in body_nodes:
        legs |= _leg_evidence(node, audit.imports)
        if isinstance(node, ast.Call):
            qual = _resolve_simple(node, audit, fn, by_qual)
            if qual is not None:
                for q in {qual} | graph.reachable({qual}):
                    legs |= lexical.get(q, set())
    missing = [leg for leg in ("cap", "backoff", "jitter")
               if leg not in legs]
    if not missing:
        return None
    if mod.suppressed(RETRY_RULE, loop.lineno):
        return None
    names = {
        "cap": "an attempt/deadline cap",
        "backoff": "backoff growth",
        "jitter": "jitter",
    }
    return _finding(
        mod, RETRY_RULE, loop.lineno,
        "retry loop around an I/O sink is missing "
        + " and ".join(names[m] for m in missing)
        + ": uncapped immediate retries synchronize into a thundering "
        "herd exactly when the server is least able to absorb one — "
        "grow the pause per failure, randomize it, and bound the "
        "attempts (or ride a stop-governed wait)",
        severity="warning",
    )


# -------------------------------------------- family 2: missing-deadline


#: boundedness lattice values: BOUNDED / UNBOUNDED / parameter-deps
_B = "B"
_U = "U"

_Bound = Tuple[str, FrozenSet[str]]  # (kind, param deps)

_BOUNDED: _Bound = (_B, frozenset())
_UNBOUNDED: _Bound = (_U, frozenset())

#: calls that read the clock (an operand of a deadline clamp, never a
#: bound by itself)
_CLOCK_CALLS = frozenset({
    "time.monotonic", "time.time", "time.perf_counter",
})


def _combine_any(parts: List[_Bound]) -> _Bound:
    """min/max/arith clamp semantics: one bounded operand bounds the
    whole expression."""
    if any(p[0] == _B and not p[1] for p in parts):
        return _BOUNDED
    deps = frozenset().union(*(p[1] for p in parts)) if parts \
        else frozenset()
    if deps:
        return (_B, deps)
    return _UNBOUNDED


def _classify_bound(
    expr: Optional[ast.AST], env: Dict[str, _Bound],
    params: Sequence[str], imports: Dict[str, str],
) -> _Bound:
    if expr is None:
        return _UNBOUNDED
    if isinstance(expr, ast.Constant):
        if expr.value is None:
            return _UNBOUNDED
        if isinstance(expr.value, (int, float)):
            return _BOUNDED
        return _BOUNDED
    if isinstance(expr, ast.Name):
        if expr.id in env:
            return env[expr.id]
        if expr.id in params:
            return (_B, frozenset({expr.id}))
        return _BOUNDED  # module constant / imported knob: optimistic
    if isinstance(expr, ast.Attribute):
        return _BOUNDED  # config attributes (self.timeout_s) trusted
    if isinstance(expr, ast.Call):
        resolved = resolve_dotted(expr.func, imports) or ""
        if resolved in _CLOCK_CALLS:
            return _UNBOUNDED
        term = _terminal(expr.func)
        if term in ("min", "max") and expr.args:
            return _combine_any([
                _classify_bound(a, env, params, imports)
                for a in expr.args
            ])
        return _BOUNDED
    if isinstance(expr, ast.BinOp):
        return _combine_any([
            _classify_bound(expr.left, env, params, imports),
            _classify_bound(expr.right, env, params, imports),
        ])
    if isinstance(expr, ast.UnaryOp):
        return _classify_bound(expr.operand, env, params, imports)
    if isinstance(expr, ast.IfExp):
        return _combine_any([
            _classify_bound(expr.body, env, params, imports),
            _classify_bound(expr.orelse, env, params, imports),
        ])
    return _BOUNDED


@dataclass
class _Sink:
    mod: Module
    fn: FnAudit
    line: int
    what: str
    bound: _Bound


@dataclass
class _ParamFacts:
    """Per-(function, parameter) boundedness material for the
    caller-path ⋂-fixpoint."""

    default: Optional[_Bound] = None  #: None = parameter has no default
    #: classifications of the argument at every resolved call site
    #: (omitted-argument sites contribute the default)
    sites: List[_Bound] = field(default_factory=list)


def _deadline_findings(
    audits: Sequence[ModuleAudit], graph: CallGraph,
) -> List[Finding]:
    by_qual: Dict[str, Tuple[ModuleAudit, FnAudit]] = {
        fn.qual: (audit, fn)
        for audit in audits for fn in audit.functions
        if fn.node is not None
    }
    closure = _deadline_closure(audits, graph)
    if not closure:
        return []
    sinks: List[_Sink] = []
    facts: Dict[Tuple[str, str], _ParamFacts] = {}
    # one walk per function: collect sinks (closure members only) and
    # call-site argument classifications (every non-exempt module — a
    # caller outside the closure still decides a parameter's bound)
    for audit in audits:
        mod = audit.module
        if _is_exempt(mod.relpath):
            continue
        for fn in audit.functions:
            if fn.node is None:
                continue
            _walk_deadline_fn(
                mod, audit, fn, fn.qual in closure, by_qual, sinks,
                facts,
            )
    unbounded = _param_fixpoint(facts, graph.depth)
    out: List[Finding] = []
    for s in sinks:
        kind, deps = s.bound
        bad_deps = sorted(d for d in deps if (s.fn.qual, d) in unbounded)
        if kind == _B and not bad_deps and not deps:
            continue
        if deps and not bad_deps:
            continue
        if s.mod.suppressed(DEADLINE_RULE, s.line):
            continue
        if bad_deps:
            msg = (
                f"{s.what} rides parameter `{bad_deps[0]}`, which is "
                "unbounded on at least one caller path (an explicit "
                "None, an unbounded forwarded parameter, or a "
                "None default with no bounded caller): thread a real "
                "deadline through every path, or clamp it at this "
                "boundary"
            )
        else:
            msg = (
                f"{s.what} has no timeout/deadline on a reconcile-path "
                "closure: under a wedged peer this blocks forever and "
                "the drain→flip→verify loop stalls with it — pass a "
                "timeout, wrap the await in asyncio.wait_for, or carry "
                "allow-missing-deadline(reason)"
            )
        out.append(_finding(s.mod, DEADLINE_RULE, s.line, msg,
                            severity="warning"))
    return out


def _deadline_closure(
    audits: Sequence[ModuleAudit], graph: CallGraph,
) -> Set[str]:
    roots: Set[str] = set()
    for audit in audits:
        mod = audit.module
        if _is_exempt(mod.relpath):
            continue
        for fn in audit.functions:
            if fn.node is None:
                continue
            if fn.name in _DEADLINE_ROOT_NAMES \
                    or mod.relpath in IO_CORE_MODULES:
                roots.add(fn.qual)
    if not roots:
        return roots
    closure = graph.reachable(roots) | roots
    # widen with nested defs of closure members (a worker closure runs
    # inside its parent's flip even without a nominal edge)
    all_quals = [
        fn.qual for audit in audits for fn in audit.functions
    ]
    while True:
        grown = set(closure)
        for q in all_quals:
            if q in grown:
                continue
            parent = q.rsplit(".", 1)[0]
            if parent in grown:
                grown.add(q)
        if grown == closure:
            return closure
        closure = grown


def _timeout_kw(call: ast.Call, name: str = "timeout") -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _sync_sink(
    call: ast.Call, imports: Dict[str, str],
) -> Optional[Tuple[str, Optional[ast.AST], bool]]:
    """(description, timeout expr or None, timeout_required) for the
    synchronous blocking sinks."""
    resolved = resolve_dotted(call.func, imports) or ""
    if resolved in ("subprocess.run", "subprocess.call",
                    "subprocess.check_call", "subprocess.check_output"):
        return (f"`{resolved}`", _timeout_kw(call), True)
    if resolved.startswith("requests."):
        return (f"`{resolved}`", _timeout_kw(call), True)
    if resolved == "select.select":
        expr = call.args[3] if len(call.args) > 3 else None
        return ("`select.select`", expr, True)
    if resolved.endswith("futures.wait") or resolved == "concurrent.futures.wait":
        return ("`concurrent.futures.wait`", _timeout_kw(call), True)
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr == "result":
            expr = call.args[0] if call.args else _timeout_kw(call)
            recv = _terminal(call.func.value) or "future"
            return (f"`{recv}.result()`", expr, True)
        if attr == "communicate":
            return ("`.communicate()`", _timeout_kw(call), True)
    return None


#: awaited attribute calls that park the coroutine until a peer acts
_ASYNC_SINK_ATTRS = frozenset({
    "read", "readline", "readexactly", "readuntil", "drain",
    "acquire", "get", "join", "wait",
})


def _walk_deadline_fn(
    mod: Module, audit: ModuleAudit, fn: FnAudit, in_closure: bool,
    by_qual: Dict[str, Tuple[ModuleAudit, FnAudit]],
    sinks: List[_Sink],
    facts: Dict[Tuple[str, str], _ParamFacts],
) -> None:
    env: Dict[str, _Bound] = {}
    params = [p for p in fn.params if p not in ("self", "cls")]
    #: await expressions already accounted via a wait_for wrapper
    wrapped: Set[int] = set()
    for node in _ordered_body(fn.node):
        if isinstance(node, ast.Assign):
            val = _classify_bound(node.value, env, params, audit.imports)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    env[tgt.id] = val
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            env[node.target.id] = _classify_bound(
                node.value, env, params, audit.imports)
        if isinstance(node, ast.Await):
            inner = node.value
            if not isinstance(inner, ast.Call):
                continue
            resolved = resolve_dotted(inner.func, audit.imports) or ""
            if resolved.endswith("wait_for"):
                for sub in ast.walk(inner):
                    wrapped.add(id(sub))
                if in_closure:
                    expr = (inner.args[1] if len(inner.args) > 1
                            else _timeout_kw(inner))
                    sinks.append(_Sink(
                        mod, fn, inner.lineno, "`asyncio.wait_for`",
                        _classify_bound(expr, env, params,
                                        audit.imports),
                    ))
                continue
            if id(inner) in wrapped or not in_closure:
                continue
            desc: Optional[str] = None
            if resolved == "asyncio.open_connection":
                desc = "awaited `asyncio.open_connection`"
            elif isinstance(inner.func, ast.Attribute) \
                    and inner.func.attr in _ASYNC_SINK_ATTRS:
                recv = _terminal(inner.func.value) or ""
                if inner.func.attr == "wait" and _STOP_NAME_RE.search(
                        recv):
                    continue  # stop-governed wait: bounded by shutdown
                if inner.func.attr in ("read", "get") and inner.args:
                    # read(n) on a non-stream / get(key) on a mapping
                    # still block, but args suggest non-timeout
                    # semantics only for dict-get; keep streams
                    if inner.func.attr == "get":
                        continue
                desc = f"awaited `{recv}.{inner.func.attr}()`"
            if desc is not None and not _timeout_kw(inner):
                sinks.append(_Sink(mod, fn, inner.lineno, desc,
                                   _UNBOUNDED))
            continue
        if not isinstance(node, ast.Call) or id(node) in wrapped:
            continue
        if in_closure and not fn.is_async:
            hit = _sync_sink(node, audit.imports)
            if hit is not None:
                what, expr, _required = hit
                sinks.append(_Sink(
                    mod, fn, node.lineno, what,
                    _classify_bound(expr, env, params, audit.imports)
                    if expr is not None else _UNBOUNDED,
                ))
        # call-site argument classification for the ⋂-fixpoint
        callee = _resolve_simple(node, audit, fn, by_qual)
        if callee is None:
            continue
        c_audit, c_fn = by_qual[callee]
        c_params = list(c_fn.params)
        offset = 0
        if c_params and c_params[0] in ("self", "cls") \
                and isinstance(node.func, ast.Attribute):
            offset = 1
        defaults = _param_defaults(c_fn)
        supplied: Set[str] = set()
        for i, arg in enumerate(node.args):
            pi = i + offset
            if pi >= len(c_params):
                break
            p = c_params[pi]
            supplied.add(p)
            facts.setdefault((callee, p), _ParamFacts(
                default=defaults.get(p),
            )).sites.append(_classify_bound(
                arg, env, params, audit.imports))
        for kw in node.keywords:
            if kw.arg is None or kw.arg not in c_params:
                continue
            supplied.add(kw.arg)
            facts.setdefault((callee, kw.arg), _ParamFacts(
                default=defaults.get(kw.arg),
            )).sites.append(_classify_bound(
                kw.value, env, params, audit.imports))
        for p in c_params:
            if p in ("self", "cls") or p in supplied:
                continue
            d = defaults.get(p)
            if d is None:
                continue  # missing required arg — not our problem
            facts.setdefault((callee, p), _ParamFacts(
                default=d,
            )).sites.append(d)


def _param_defaults(fn: FnAudit) -> Dict[str, _Bound]:
    """Classification of each defaulted parameter's default value."""
    out: Dict[str, _Bound] = {}
    args = fn.node.args
    pos = args.args
    for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
        out[a.arg] = _classify_bound(d, {}, [], {})
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None:
            out[a.arg] = _classify_bound(d, {}, [], {})
    return out


def _param_fixpoint(
    facts: Dict[Tuple[str, str], _ParamFacts], depth: int,
) -> Set[Tuple[str, str]]:
    """Greatest-fixpoint ⋂ over caller paths: a (function, parameter)
    is UNBOUNDED when any resolved call site passes an unbounded value
    (transitively through the caller's own parameters), or when it has
    no resolved sites and its default is unbounded."""
    unbounded: Set[Tuple[str, str]] = set()

    def site_ok(qual: str, b: _Bound) -> bool:
        kind, deps = b
        if kind == _U and not deps:
            return False
        return all((qual, d) not in unbounded for d in deps)

    for _ in range(max(2, depth)):
        changed = False
        for (qual, p), pf in facts.items():
            if (qual, p) in unbounded:
                continue
            bad = False
            if not pf.sites:
                bad = pf.default is not None and pf.default == _UNBOUNDED
            else:
                bad = not all(site_ok(qual, b) for b in pf.sites)
            if bad:
                unbounded.add((qual, p))
                changed = True
        if not changed:
            break
    return unbounded
