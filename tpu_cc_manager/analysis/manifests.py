"""ccaudit manifest cross-check — code↔manifest protocol drift.

The kustomize/manifest tree and the simlab scenario files carry their own
copies of the cluster-visible protocol: label/taint keys in tolerations,
nodeAffinity and webhook objectSelectors, the TPUCCPolicy CRD's ``mode``
enum, and the desired-mode strings scenario timelines patch onto nodes.
None of that YAML/JSON is visible to the AST rules, so a constant renamed
in ``labels.py`` (or a Mode member added to ``modes.py``) would leave the
deploy tree silently advertising a protocol the code no longer speaks —
a fleet-wide correctness bug no test executes.

This pass closes the loop, in both directions:

- **manifest → code**: every ``*.google.com/...``-shaped key anywhere in
  the manifest tree must equal a value exported by ``labels.py``, and
  every ``mode``/``initial_mode`` string value in a scenario or CRD must
  be a ``modes.VALID_MODES`` member;
- **code → manifest**: every TPUCCPolicy CRD ``mode`` enum must equal
  ``VALID_MODES`` *exactly* — so adding a Mode member fails CI until the
  CRD (and therefore the cluster's admission surface) learns it too.

Findings carry the matched line so they flow through the same baseline
ratchet as every AST rule; YAML lines can be pragma'd
(``# ccaudit: allow-manifest-drift(reason)``), JSON (no comments) is
baseline-only. The file set is deliberately a loud contract: a glob that
matches nothing fails, because a gate that quietly stops scanning is
worse than none (the same stance ``core.iter_python_files`` takes).
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re
import sys
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from tpu_cc_manager import labels as _labels
from tpu_cc_manager.analysis.core import PRAGMA_RE, Finding
from tpu_cc_manager.modes import VALID_MODES

RULE = "manifest-drift"

#: Scanned manifest surface, relative to the repo root.
MANIFEST_GLOBS = (
    "deployments/kustomize/*.yaml",
    "deployments/manifests/*.yaml",
    "scenarios/*.json",
)

#: ``<something>.google.com/<path>`` — requires at least one subdomain
#: label before ``google.com``, so the plain ``google.com/tpu`` extended
#: resource toleration doesn't match. Built to cover both the
#: tpu.google.com and cloud.google.com protocol families.
_KEY_RE = re.compile(
    r"[A-Za-z0-9-]+(?:\.[A-Za-z0-9-]+)*\.google\.com/[A-Za-z0-9._-]+"
)

#: JSON/YAML object keys whose string value is a desired mode.
#: ``rival_mode`` is the policy_conflict fault's second claim (ISSUE
#: 12) — a typo'd mode there would otherwise only fail at load time.
_MODE_FIELDS = ("mode", "initial_mode", "rival_mode")


def code_protocol_keys() -> Set[str]:
    """Every ``*.google.com/...`` key the code exports from labels.py —
    pulled from the live module so the check can never drift from the
    source of truth it is defending."""
    keys: Set[str] = set()

    def harvest(value: object) -> None:
        if isinstance(value, str):
            keys.update(_KEY_RE.findall(value))
        elif isinstance(value, (tuple, list, frozenset, set)):
            for v in value:
                harvest(v)
        elif isinstance(value, dict):
            for k, v in value.items():
                harvest(k)
                harvest(v)

    for name in dir(_labels):
        if name.startswith("_"):
            continue
        harvest(getattr(_labels, name))
    # the CRD/CR apiVersion composite is protocol too, derived from the
    # same constants
    keys.add(f"{_labels.POLICY_GROUP}/{_labels.POLICY_VERSION}")
    return keys


def _finding(
    relpath: str,
    lines: Sequence[str],
    lineno: int,
    message: str,
) -> Optional[Finding]:
    text = lines[lineno - 1].strip() if 1 <= lineno <= len(lines) else ""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            for m in PRAGMA_RE.finditer(lines[ln - 1]):
                if m.group(1) == RULE:
                    return None
    return Finding(
        file=relpath, line=lineno, rule=RULE, message=message, text=text
    )


def _find_line(
    lines: Sequence[str], needle: str, start: int = 1
) -> Optional[int]:
    """First line >= ``start`` containing ``needle``, 1-indexed."""
    for i in range(start - 1, len(lines)):
        if needle in lines[i]:
            return i + 1
    return None


def _scan_keys(
    relpath: str, lines: Sequence[str], known: Set[str]
) -> Iterable[Finding]:
    for i, line in enumerate(lines, start=1):
        for key in _KEY_RE.findall(line):
            if key in known:
                continue
            f = _finding(
                relpath, lines, i,
                f"protocol key {key!r} has no labels.py counterpart — "
                "the manifest tree and the code have drifted (rename the "
                "manifest key or export the constant)",
            )
            if f is not None:
                yield f


def _walk_string_fields(
    doc: object, keys: Sequence[str], path: str = "$"
) -> Iterable[Tuple[str, str]]:
    """Yield (json-path, value) for every string field named in
    ``keys`` anywhere in a parsed document — the one traversal behind
    both the mode-field and fault-kind scans."""
    if isinstance(doc, dict):
        for k, v in doc.items():
            if k in keys and isinstance(v, str):
                yield f"{path}.{k}", v
            yield from _walk_string_fields(v, keys, f"{path}.{k}")
    elif isinstance(doc, list):
        for idx, v in enumerate(doc):
            yield from _walk_string_fields(v, keys, f"{path}[{idx}]")


def _walk_mode_fields(
    doc: object, path: str = "$"
) -> Iterable[Tuple[str, str]]:
    """Yield (json-path, value) for every mode-valued field in a parsed
    document."""
    return _walk_string_fields(doc, _MODE_FIELDS, path)


def scenario_fault_kinds() -> Set[str]:
    """The live simlab fault vocabulary — pulled from the scenario
    schema itself so this check can never drift from the validator it
    fronts for (the labels.py treatment, applied to fault kinds)."""
    from tpu_cc_manager.simlab.scenario import FAULT_PARAMS

    return set(FAULT_PARAMS)


def _walk_fault_kinds(
    doc: object, path: str = "$"
) -> Iterable[Tuple[str, str]]:
    """Yield (json-path, value) for every ``"fault": "<kind>"`` field
    in a parsed scenario document."""
    return _walk_string_fields(doc, ("fault",), path)


def _scan_scenario(
    relpath: str, raw: str, lines: Sequence[str], valid: Set[str],
    faults: Optional[Set[str]] = None,
) -> Iterable[Finding]:
    try:
        doc = json.loads(raw)
    except ValueError as e:
        f = _finding(relpath, lines, 1, f"unparseable scenario JSON: {e}")
        if f is not None:
            yield f
        return
    if faults is None:
        faults = scenario_fault_kinds()
    for path, value in _walk_fault_kinds(doc):
        if value in faults:
            continue
        lineno = (
            _find_line(lines, f'"fault": "{value}"')
            or _find_line(lines, f'"{value}"')
            or 1
        )
        f = _finding(
            relpath, lines, lineno,
            f"{path} = {value!r} is not a simlab FAULT_PARAMS kind — "
            "the scenario would be rejected at load; fix the literal "
            "or teach scenario.FAULT_PARAMS (and faults.FaultInjector) "
            "the new fault first",
        )
        if f is not None:
            yield f
    for path, value in _walk_mode_fields(doc):
        if value in valid:
            continue
        # anchor on the `"<key>": "<value>"` pair (scenarios are
        # canonically formatted), falling back to the bare value
        key = path.rsplit(".", 1)[-1]
        lineno = (
            _find_line(lines, f'"{key}": "{value}"')
            or _find_line(lines, f'"{value}"')
            or 1
        )
        f = _finding(
            relpath, lines, lineno,
            f"{path} = {value!r} is not a modes.VALID_MODES member — the "
            "scenario would be rejected at load; fix the literal or add "
            "the mode to modes.py first",
        )
        if f is not None:
            yield f


_warned_no_yaml = False


def _warn_no_yaml() -> None:
    """pyyaml missing: the structured YAML checks (CRD mode enum) are
    skipped — loudly, once, like the ruff/mypy skip notices. The regex
    key scan still runs, so the acceptance-critical direction holds."""
    global _warned_no_yaml
    if not _warned_no_yaml:
        _warned_no_yaml = True
        print(
            "ccaudit: pyyaml not installed; skipping the structured "
            "manifest checks (pip install -r requirements-dev.txt)",
            file=sys.stderr,
        )


def _crd_mode_enums(doc: object) -> Iterable[List[str]]:
    """Every ``mode: {enum: [...]}`` property in a parsed YAML document —
    the TPUCCPolicy CRD today, any CR example tomorrow."""
    if isinstance(doc, dict):
        mode = doc.get("mode")
        if isinstance(mode, dict) and isinstance(mode.get("enum"), list):
            yield mode["enum"]
        for v in doc.values():
            yield from _crd_mode_enums(v)
    elif isinstance(doc, list):
        for v in doc:
            yield from _crd_mode_enums(v)


def _scan_yaml(
    relpath: str, raw: str, lines: Sequence[str], valid: Set[str]
) -> Iterable[Finding]:
    try:
        import yaml
    except ImportError:  # pragma: no cover - pyyaml is a dev/CI dep
        _warn_no_yaml()
        return
    try:
        docs = [d for d in yaml.safe_load_all(raw) if d is not None]
    except yaml.YAMLError as e:
        # a manifest the cluster would reject is drift too — a gate that
        # quietly stops scanning is worse than none
        mark = getattr(e, "problem_mark", None)
        lineno = mark.line + 1 if mark is not None else 1
        detail = " ".join(str(e).split())
        f = _finding(
            relpath, lines, lineno,
            f"unparseable manifest YAML: {detail}",
        )
        if f is not None:
            yield f
        return
    # successive enums anchor successively (multi-document files: the
    # cursor keeps finding N from landing on enum N-1's line, which
    # would break line-based pragmas and go stale in the baseline)
    cursor = 1
    for doc in docs:
        for enum in _crd_mode_enums(doc):
            enum_set = {str(v) for v in enum}
            anchor = _find_line(lines, "enum:", cursor) or cursor
            for extra in sorted(enum_set - valid):
                f = _finding(
                    relpath, lines,
                    _find_line(lines, extra, anchor) or anchor,
                    f"CRD mode enum value {extra!r} is not a "
                    "modes.VALID_MODES member — the admission surface "
                    "accepts a mode the code rejects",
                )
                if f is not None:
                    yield f
            for missing in sorted(valid - enum_set):
                f = _finding(
                    relpath, lines, anchor,
                    f"CRD mode enum is missing {missing!r} — modes.py "
                    "learned a mode the admission surface still rejects; "
                    "regenerate the manifests",
                )
                if f is not None:
                    yield f
            cursor = anchor + 1


def manifest_findings(
    root: str,
    globs: Sequence[str] = MANIFEST_GLOBS,
    known_keys: Optional[Set[str]] = None,
    valid_modes: Optional[Set[str]] = None,
    known_faults: Optional[Set[str]] = None,
) -> List[Finding]:
    """Run the cross-check over ``root``. ``known_keys`` /
    ``valid_modes`` / ``known_faults`` default to the live labels.py /
    modes.py / simlab schema exports; tests inject their own to build
    drift fixtures."""
    known = code_protocol_keys() if known_keys is None else set(known_keys)
    valid = set(VALID_MODES) if valid_modes is None else set(valid_modes)
    faults = (scenario_fault_kinds() if known_faults is None
              else set(known_faults))

    findings: List[Finding] = []
    for pattern in globs:
        paths = sorted(_glob.glob(os.path.join(root, pattern)))
        if not paths:
            raise FileNotFoundError(
                f"manifest cross-check glob {pattern!r} matched no files "
                f"under {root}"
            )
        for path in paths:
            relpath = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, "r", encoding="utf-8") as f:
                raw = f.read()
            lines = raw.splitlines()
            findings.extend(_scan_keys(relpath, lines, known))
            if relpath.endswith(".json"):
                findings.extend(
                    _scan_scenario(relpath, raw, lines, valid, faults)
                )
            else:
                findings.extend(_scan_yaml(relpath, raw, lines, valid))
    return sorted(set(findings))
