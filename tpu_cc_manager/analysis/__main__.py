"""ccaudit CLI: ``python -m tpu_cc_manager.analysis``.

Exit 0 when the repo is clean against the committed baseline; exit 1 on
any new finding *or* any stale baseline entry (the ratchet only turns one
way — see baseline.py). ``make lint`` and the CI ``ccaudit`` job both run
exactly this."""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from tpu_cc_manager.analysis import baseline as baseline_mod
from tpu_cc_manager.analysis.core import (
    DEFAULT_TARGETS,
    analyze_paths,
    on_default_surface,
    repo_root,
)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_cc_manager.analysis",
        description="ccaudit: whole-program concurrency + protocol "
        "analyzer (lock discipline, transitive ABBA lock order, "
        "blocking-under-lock through the call graph, Eraser-style "
        "race-lockset over thread-shared state, label hygiene, "
        "exception discipline, metric-name consistency, protocol-literal "
        "confinement, unvalidated-mode taint, Mode exhaustiveness, "
        "protocol liveness, code<->manifest drift, the v4 async "
        "families: await-atomicity, lock-across-await, loop-affinity "
        "typestate, loop self-deadlock, orphan tasks, async-exception "
        "fail-secure, and the v5 jitflow families over the JAX "
        "dispatch surface: retrace hazards vs the bucket ladder, "
        "host-sync stalls in hot paths, unserialized collective "
        "dispatch, donated-buffer reuse, tracer leaks, and the v6 "
        "resourceflow families: unbounded queues, missing deadlines on "
        "the reconcile closure, retry discipline, resource leak paths, "
        "stop-aware waits). "
        "docs/analysis.md has the rule contract.",
    )
    parser.add_argument(
        "targets", nargs="*", default=list(DEFAULT_TARGETS),
        help="files/directories to scan, relative to --root "
        f"(default: {' '.join(DEFAULT_TARGETS)})",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root (default: auto-detected from the package location)",
    )
    parser.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: <root>/{baseline_mod.BASELINE_PATH})",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report and fail on every finding",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline from current findings and exit 0",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as JSON instead of text",
    )
    parser.add_argument(
        "--sarif", default=None, metavar="PATH",
        help="also write the scan as SARIF 2.1.0 to PATH (new findings "
        "level=error, baselined ones suppressed notes, stale entries "
        "stale-baseline errors) — CI uploads this so findings annotate "
        "PR diffs",
    )
    parser.add_argument(
        "--call-depth", type=int, default=None, metavar="N",
        help="transitive call-graph horizon in call edges beyond the "
        "direct callee (default: callgraph.DEPTH_LIMIT; 0 restricts "
        "summaries to the direct callee, the v2 one-hop horizon — the "
        "escape hatch when a refactor needs a different bound)",
    )
    group = parser.add_mutually_exclusive_group()
    group.add_argument(
        "--manifests", action="store_true",
        help="force the code<->manifest cross-check even with explicit "
        "targets",
    )
    group.add_argument(
        "--no-manifests", action="store_true",
        help="skip the code<->manifest cross-check (it runs by default "
        "on the default scan surface)",
    )
    parser.add_argument(
        "--files", action="store_true",
        help="changed-files mode: treat targets as an explicit file "
        "list and report ONLY findings in those files. Non-Python, "
        "missing, and off-surface paths (tests/ — the merge gate never "
        "scans them) are silently skipped: a diff includes deletions "
        "and docs. The analysis itself still runs whole-program over "
        "the default surface, so the report is exactly the full run's "
        "findings restricted to the slice — only the manifest "
        "cross-check is skipped, and stale baseline entries are "
        "ignored (entries for out-of-slice files are out of scope, "
        "not stale). `make lint-fast` wires this to the git diff.",
    )
    parser.add_argument(
        "--cache", action="store_true",
        help="route the per-module parse stage through the "
        "content-hash fact cache (<root>/.ccaudit_cache/): unchanged "
        "modules reload pickled facts, only edited ones re-parse. The "
        "whole-program passes still run fresh over every module, so a "
        "cached scan reports exactly what an uncached one would; keys "
        "embed an analyzer-source digest, so rule edits self-"
        "invalidate. `make lint-fast` turns this on.",
    )
    args = parser.parse_args(argv)

    with_manifests: Optional[bool] = None
    if args.manifests:
        with_manifests = True
    elif args.no_manifests:
        with_manifests = False

    root = os.path.abspath(args.root) if args.root else repo_root()
    baseline_path = args.baseline or os.path.join(
        root, baseline_mod.BASELINE_PATH
    )

    targets = list(args.targets)
    if args.files:
        targets = [
            t for t in targets
            if t.endswith(".py")
            and os.path.isfile(os.path.join(root, t))
            and on_default_surface(t)
        ]
        if not targets:
            print("ccaudit: --files: nothing to scan", file=sys.stderr)
            return 0

    try:
        findings = analyze_paths(
            root, targets, with_manifests, call_depth=args.call_depth,
            subset=args.files, cache=args.cache,
        )
    except FileNotFoundError as e:
        print(f"ccaudit: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        baseline_mod.write_baseline(findings, baseline_path)
        print(
            f"ccaudit: wrote {len(findings)} finding(s) to {baseline_path}"
        )
        return 0

    entries = [] if args.no_baseline else baseline_mod.load_baseline(
        baseline_path
    )
    new, suppressed, stale = baseline_mod.diff_against_baseline(
        findings, entries
    )
    if args.files:
        # the report covers only the changed slice: baseline entries
        # for files outside it are out of scope, not stale
        stale = []

    if args.sarif:
        from tpu_cc_manager.analysis import sarif as sarif_mod

        sarif_mod.write_sarif(args.sarif, new, suppressed, stale)

    if args.as_json:
        print(json.dumps(
            {
                "new": [f.to_json() for f in new],
                "suppressed": [f.to_json() for f in suppressed],
                "stale": stale,
            },
            indent=1, sort_keys=True,
        ))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(
                f"{e.get('file')}:{e.get('line')}: [stale-baseline] entry "
                f"for rule {e.get('rule')!r} matches no current finding — "
                "delete it (or --write-baseline)"
            )
        print(
            f"ccaudit: {len(new)} new finding(s), {len(stale)} stale "
            f"baseline entr{'y' if len(stale) == 1 else 'ies'}, "
            f"{len(suppressed)} baselined",
            file=sys.stderr,
        )
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
