"""ccaudit JAX-dispatch whole-program pass (v5 "jitflow").

ROADMAP item 1 (the million-node planner: delta ticks on a multi-host
mesh) multiplies the repo's JAX dispatch surface — more jitted kernels,
donated buffers, mesh-spanning ``shard_map`` programs. v1–v4 see locks,
dataflow, threads and the event loop, but are blind to the hazard class
that dominates a jit-heavy control plane: silent retraces (a multi-second
XLA compile in the tick path), host↔device sync stalls on hot paths,
dispatch outside the ``_DISPATCH_LOCK`` contract (plan.py:746 — PR 7's
5 s rendezvous stalls), and donated-buffer reuse. This module teaches
the analyzer the dispatch model — five gated rule families over the same
per-function records and call graph the thread/async passes consume
(docs/analysis.md §v5 has the full contract):

``retrace-hazard``
    Every distinct static-argument value and every distinct input
    geometry retraces a jitted callable. The sanctioned way to feed the
    planner kernels is the power-of-two bucket ladder
    (``bucket_nodes``/``bucket_pools``), so shape/static arguments are
    classified on a three-point provenance lattice — CONST (literals,
    ``UPPER_CASE`` module constants, arithmetic over them) ⊑ BUCKETED
    (results of the bucket functions, values read off a ``.bucket``-named
    snapshot attribute, arithmetic that stays within the ladder) ⊑
    DYNAMIC (``len()``, ``.shape``, parameters, anything else). A jit
    factory (a function whose body builds a ``jax.jit`` program from its
    geometry parameters, e.g. ``plan._tick_fn``) invoked with a DYNAMIC
    geometry argument, or a jit root invoked with a DYNAMIC value at a
    ``static_argnums``/``static_argnames`` position, fires. Pragma:
    ``allow-retrace(reason)``.

``host-sync-in-hot-path``
    Implicit device→host transfers on values returned by a jitted
    callable — ``float()``/``int()``/``bool()``/``np.asarray()``/
    ``.item()``/iteration — and any ``.block_until_ready()`` reachable
    from the reconcile/scan/tick call paths each stall the dispatching
    thread on device completion. ``jax.device_get`` is the sanctioned
    explicit transfer (its result is host-side and exempt). bench/
    scripts/simlab modules are exempt — they measure or simulate, and
    blocking there is the point. Pragma: ``allow-host-sync(reason)``.

``unserialized-dispatch``
    The sharded tick is a multi-participant collective program; XLA's
    cross-module all-reduce rendezvous must not interleave from
    multiple host threads (plan.py:746). Every call site of a
    ``shard_map``-wrapped jitted callable must hold ``_DISPATCH_LOCK``
    — lexically or via the caller-held ⋂-fixpoint the race pass already
    computes (``lockset.caller_held_locks``, the ``_locked``-suffix
    convention). AOT ``.lower()``/``.compile()`` are not dispatches.
    The one guaranteed-incident shape in the family: **error** severity.

``donation-violation``
    ``donate_argnums``/``donate_argnames`` hand the argument's buffer to
    XLA — after the call the Python reference points at freed device
    memory. A read of a donated argument after the donating call (v2
    statement-order) fires. Pragma: ``allow-donation(reason)``.

``tracer-leak``
    Inside a traced function body, Python runs once per (re)trace, not
    per step: a write to a ``self.``-attribute or module global is a
    trace-time side effect (deliberate ones — the ``TRACE_COUNTS``
    retrace pin — carry a pragma), and an ``if``/``while`` on a traced
    array value raises ``TracerBoolConversionError`` at trace time.
    Conditions on ``static_argnames`` parameters, keyword-only config
    parameters, and ``is None`` defaulting are Python-level and exempt.

All five ids take ``# ccaudit: allow-<rule>(reason)`` pragmas; the
retrace/host-sync/donation families also accept the short aliases
``allow-retrace``/``allow-host-sync``/``allow-donation``. New findings
surface at SARIF level ``warning`` except ``unserialized-dispatch``
(``error``); the baseline ratchet gates them all identically.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import (
    Dict,
    FrozenSet,
    Iterator,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from tpu_cc_manager.analysis import lockset
from tpu_cc_manager.analysis.callgraph import CallGraph
from tpu_cc_manager.analysis.core import (
    Finding,
    Module,
    resolve_dotted,
)
from tpu_cc_manager.analysis.rules import FnAudit, ModuleAudit
from tpu_cc_manager.analysis.threads import ThreadRoot

RETRACE_RULE = "retrace-hazard"
SYNC_RULE = "host-sync-in-hot-path"
DISPATCH_RULE = "unserialized-dispatch"
DONATION_RULE = "donation-violation"
TRACER_RULE = "tracer-leak"

#: every v5 family, in contract order (bench stamps this count so the
#: smoke job can assert the pass actually ran)
JITFLOW_RULES = (
    RETRACE_RULE, SYNC_RULE, DISPATCH_RULE, DONATION_RULE, TRACER_RULE,
)

#: v5 ids that enter at SARIF ``warning``; ``unserialized-dispatch`` is
#: the one guaranteed-incident shape (PR 7's rendezvous stalls) and
#: stays ``error``.
WARNING_RULES = frozenset({
    RETRACE_RULE, SYNC_RULE, DONATION_RULE, TRACER_RULE,
})

#: short pragma spellings the ISSUE contract names
#: (``allow-retrace(reason)`` etc.) — accepted alongside the full ids
PRAGMA_ALIASES = {
    RETRACE_RULE: "retrace",
    SYNC_RULE: "host-sync",
    DONATION_RULE: "donation",
}

#: terminal names of the sanctioned bucket-ladder functions — their
#: results are BUCKETED by definition
_BUCKET_FNS = frozenset({"bucket_nodes", "bucket_pools",
                         "bucket_deltas"})

#: attribute names that carry a bucket by convention: a snapshot that
#: computed its own bucket exposes it under ``.bucket`` (FleetSnapshot),
#: the same way the ``_locked`` suffix carries a lockset contract
_BUCKET_ATTRS = frozenset({"bucket", "node_bucket", "pool_bucket",
                           "delta_bucket"})

#: function names that anchor the hot host paths: the controllers'
#: reconcile/scan bodies and the planner's host API. Name-matched under
#: ``tpu_cc_manager/`` (simlab excluded below) so the set survives
#: refactors that move them between classes.
_HOT_ROOT_NAMES = frozenset({
    "reconcile", "scan_once", "_scan",
    "analyze_fleet", "analyze_encoding", "analyze_pools",
})

#: module prefixes exempt from the retrace + host-sync advisories:
#: benches measure sync stalls on purpose, scripts are one-shot CLIs,
#: simlab drives wall-clock scenarios. __graft_entry__ is deliberately
#: NOT exempt — its dry-run pragmas are the worked suppression example.
_EXEMPT_PREFIXES = ("bench.py", "scripts/", "tpu_cc_manager/simlab/")

#: the process-wide dispatch serializer (plan.py:746) — matched by
#: terminal name so the contract survives a module move
_DISPATCH_LOCK_NAME = "_DISPATCH_LOCK"

#: provenance lattice points, in increasing order of hazard
_CONST, _BUCKETED, _DYNAMIC = 0, 1, 2
_PROV_NAMES = {_CONST: "constant", _BUCKETED: "bucketed", _DYNAMIC: "dynamic"}


def _is_exempt(relpath: str) -> bool:
    return any(
        relpath == p or relpath.startswith(p) for p in _EXEMPT_PREFIXES
    )


def _suppressed(mod: Module, rule: str, line: int) -> bool:
    if mod.suppressed(rule, line):
        return True
    alias = PRAGMA_ALIASES.get(rule)
    return alias is not None and mod.suppressed(alias, line)


def _finding(mod: Module, rule: str, line: int, message: str) -> Finding:
    return Finding(
        file=mod.relpath,
        line=line,
        rule=rule,
        message=message,
        text=mod.line_text(line),
        severity="warning" if rule in WARNING_RULES else "error",
    )


def _ordered_body(fn: ast.AST) -> Iterator[ast.AST]:
    """Preorder, source-ordered nodes lexically inside ``fn``, not
    descending into nested defs (separate execution contexts — a nested
    def's body runs when *it* is called, not where it is defined)."""
    for child in ast.iter_child_nodes(fn):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from _ordered_body(child)


# -------------------------------------------------------- jit inventory


@dataclass
class JitRoot:
    """One jitted callable binding: ``name = jax.jit(...)`` or a
    ``@jax.jit``-decorated function."""

    name: str
    #: dotted qual of the scope that owns the binding — the module for
    #: module-level roots, the enclosing function's qual for locals
    owner: str
    module: str  #: relpath
    line: int
    #: wrapped by ``shard_map`` (directly or via a wrapped local) — the
    #: collective programs the dispatch-lock contract covers
    collective: bool = False
    static_argnames: Tuple[str, ...] = ()
    static_argnums: Tuple[int, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    donate_argnames: Tuple[str, ...] = ()
    #: qual of the traced Python function, when nominally resolvable
    target: Optional[str] = None


@dataclass
class JitFactory:
    """A function whose body builds a jit program from its parameters
    (``plan._tick_fn``): every distinct argument tuple is a distinct
    compile, so its call sites are geometry sites."""

    name: str
    qual: str
    module: str
    line: int
    params: Tuple[str, ...] = ()


@dataclass
class Inventory:
    roots: List[JitRoot] = field(default_factory=list)
    factories: List[JitFactory] = field(default_factory=list)

    def visible_roots(self, fn_qual: str, moddot: str) -> Dict[str, JitRoot]:
        """Roots a bare name inside ``fn_qual`` (module ``moddot``) can
        refer to: module-level bindings of the same module plus bindings
        of any enclosing scope (closures — ``run`` sees ``_tick_fn``'s
        ``jitted``). Innermost binding wins."""
        out: Dict[str, JitRoot] = {}
        candidates = [
            r for r in self.roots
            if r.owner == moddot
            or r.owner == fn_qual
            or fn_qual.startswith(r.owner + ".")
        ]
        candidates.sort(key=lambda r: len(r.owner))
        for r in candidates:
            out[r.name] = r
        return out

    def root_by_qual(self, qual: Optional[str]) -> Optional[JitRoot]:
        """Module-level root matched by import-folded dotted path
        (``plan.fleet_plan_jit`` from another module)."""
        if not qual:
            return None
        for r in self.roots:
            if f"{r.owner}.{r.name}" == qual:
                return r
        return None

    def factory_for(
        self, bare: Optional[str], resolved: Optional[str], moddot: str
    ) -> Optional[JitFactory]:
        for f in self.factories:
            if bare and f.qual == f"{moddot}.{bare}":
                return f
            if resolved and f.qual == resolved:
                return f
        return None


def _str_tuple(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


def _int_tuple(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        )
    return ()


def _call_terminal(call: ast.Call, imports: Dict[str, str]) -> Optional[str]:
    resolved = resolve_dotted(call.func, imports)
    if resolved:
        return resolved.rsplit(".", 1)[-1]
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _is_jit_call(call: ast.Call, imports: Dict[str, str]) -> bool:
    resolved = resolve_dotted(call.func, imports) or ""
    return resolved == "jax.jit" or resolved.endswith(".jit")


def _is_shard_map_call(call: ast.Call, imports: Dict[str, str]) -> bool:
    term = _call_terminal(call, imports)
    return term is not None and term.lstrip("_") == "shard_map"


def _jit_config(call: ast.Call) -> Dict[str, Tuple]:
    cfg: Dict[str, Tuple] = {}
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            cfg["static_argnames"] = _str_tuple(kw.value)
        elif kw.arg == "static_argnums":
            cfg["static_argnums"] = _int_tuple(kw.value)
        elif kw.arg == "donate_argnums":
            cfg["donate_argnums"] = _int_tuple(kw.value)
        elif kw.arg == "donate_argnames":
            cfg["donate_argnames"] = _str_tuple(kw.value)
    return cfg


def _unwrap_partial(
    node: ast.AST, imports: Dict[str, str]
) -> Optional[ast.Call]:
    """``partial(jax.jit, ...)`` / ``partial(shard_map, ...)`` decorator
    → a synthetic Call on the inner callable carrying partial's
    keywords, so decorator detection sees one shape."""
    if not isinstance(node, ast.Call):
        return None
    resolved = resolve_dotted(node.func, imports) or ""
    if not resolved.endswith("partial") or not node.args:
        return None
    inner = ast.Call(
        func=node.args[0], args=list(node.args[1:]),
        keywords=list(node.keywords),
    )
    return ast.copy_location(inner, node)


def build_inventory(audits: Sequence[ModuleAudit]) -> Inventory:
    """One scoped walk per module containing jit/shard_map text: every
    jit binding, every shard_map wrap, every jit factory."""
    inv = Inventory()
    for audit in audits:
        mod = audit.module
        if "jit" not in mod.source and "shard_map" not in mod.source:
            continue
        _InventoryWalk(audit, inv).walk(mod.tree, audit.dotted)
    return inv


class _InventoryWalk:
    def __init__(self, audit: ModuleAudit, inv: Inventory):
        self.audit = audit
        self.mod = audit.module
        self.imports = audit.imports
        self.inv = inv

    def walk(self, scope_node: ast.AST, owner: str) -> None:
        #: local names bound to a shard_map result in this scope,
        #: mapped to the wrapped callable's bare name (if nominal)
        collective_locals: Dict[str, Optional[str]] = {}
        for node in ast.iter_child_nodes(scope_node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._function(node, owner)
            elif isinstance(node, ast.ClassDef):
                self.walk(node, f"{owner}.{node.name}")
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                self._assign(
                    node.targets[0].id, node.value, node, owner,
                    collective_locals,
                )
            elif isinstance(node, (ast.If, ast.Try, ast.With, ast.For,
                                   ast.While)):
                # bindings behind guards (`try: from jax import ...`)
                # still bind the scope's name
                self.walk_stmts(node, owner, collective_locals)

    def walk_stmts(
        self, node: ast.AST, owner: str,
        collective_locals: Dict[str, Optional[str]],
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._function(child, owner)
            elif isinstance(child, ast.Assign) and len(child.targets) == 1 \
                    and isinstance(child.targets[0], ast.Name):
                self._assign(
                    child.targets[0].id, child.value, child, owner,
                    collective_locals,
                )
            elif isinstance(child, (ast.If, ast.Try, ast.With, ast.For,
                                    ast.While, ast.ExceptHandler)):
                self.walk_stmts(child, owner, collective_locals)

    def _assign(
        self, name: str, value: ast.AST, node: ast.AST, owner: str,
        collective_locals: Dict[str, Optional[str]],
    ) -> None:
        if not isinstance(value, ast.Call):
            return
        if _is_shard_map_call(value, self.imports):
            wrapped = value.args[0] if value.args else None
            collective_locals[name] = (
                wrapped.id if isinstance(wrapped, ast.Name) else None
            )
            return
        if _is_jit_call(value, self.imports):
            cfg = _jit_config(value)
            target_name: Optional[str] = None
            collective = False
            if value.args and isinstance(value.args[0], ast.Name):
                arg0 = value.args[0].id
                if arg0 in collective_locals:
                    collective = True
                    target_name = collective_locals[arg0]
                else:
                    target_name = arg0
            target = f"{owner}.{target_name}" if target_name else None
            self.inv.roots.append(JitRoot(
                name=name, owner=owner, module=self.mod.relpath,
                line=node.lineno, collective=collective,
                target=target, **cfg,
            ))

    def _function(self, node: ast.AST, owner: str) -> None:
        qual = f"{owner}.{node.name}"
        jit_deco = False
        collective = False
        cfg: Dict[str, Tuple] = {}
        for deco in node.decorator_list:
            eff = _unwrap_partial(deco, self.imports) or deco
            if isinstance(eff, ast.Call):
                if _is_jit_call(eff, self.imports):
                    jit_deco = True
                    cfg.update(_jit_config(eff))
                elif _is_shard_map_call(eff, self.imports):
                    collective = True
            else:
                resolved = resolve_dotted(eff, self.imports) or ""
                if resolved == "jax.jit" or resolved.endswith(".jit"):
                    jit_deco = True
        if jit_deco:
            self.inv.roots.append(JitRoot(
                name=node.name, owner=owner, module=self.mod.relpath,
                line=node.lineno, collective=collective, target=qual,
                **cfg,
            ))
        # a jit factory: builds a jax.jit program in its own body from
        # its parameters — each distinct argument tuple is a compile
        has_jit = any(
            isinstance(n, ast.Call) and _is_jit_call(n, self.imports)
            for n in _ordered_body(node)
        )
        params = tuple(
            a.arg for a in node.args.args if a.arg not in ("self", "cls")
        )
        if has_jit and params:
            self.inv.factories.append(JitFactory(
                name=node.name, qual=qual, module=self.mod.relpath,
                line=node.lineno, params=params,
            ))
        self.walk(node, qual)


# ------------------------------------------------- provenance lattice


def _is_const_name(name: str) -> bool:
    return name == name.upper() and any(c.isalpha() for c in name)


def _classify(
    expr: ast.AST, prov: Dict[str, int], imports: Dict[str, str],
) -> int:
    """Three-point shape-provenance lattice (docs/analysis.md §v5):
    CONST ⊑ BUCKETED ⊑ DYNAMIC; combinations take the max."""
    if isinstance(expr, ast.Constant):
        return _CONST
    if isinstance(expr, ast.Name):
        if expr.id in prov:
            return prov[expr.id]
        return _CONST if _is_const_name(expr.id) else _DYNAMIC
    if isinstance(expr, ast.Attribute):
        if expr.attr in _BUCKET_ATTRS:
            return _BUCKETED
        return _CONST if _is_const_name(expr.attr) else _DYNAMIC
    if isinstance(expr, ast.Call):
        term = _call_terminal(expr, imports)
        if term in _BUCKET_FNS:
            return _BUCKETED
        if term in ("max", "min") and expr.args:
            return max(_classify(a, prov, imports) for a in expr.args)
        return _DYNAMIC
    if isinstance(expr, ast.BinOp):
        return max(_classify(expr.left, prov, imports),
                   _classify(expr.right, prov, imports))
    if isinstance(expr, ast.UnaryOp):
        return _classify(expr.operand, prov, imports)
    if isinstance(expr, ast.IfExp):
        return max(_classify(expr.body, prov, imports),
                   _classify(expr.orelse, prov, imports))
    if isinstance(expr, (ast.Tuple, ast.List)) and expr.elts:
        return max(_classify(e, prov, imports) for e in expr.elts)
    return _DYNAMIC


def _track_assign(
    node: ast.AST, prov: Dict[str, int], imports: Dict[str, str],
) -> None:
    """Fold one statement into the provenance environment (last write
    wins — branch-insensitive, which is the right linter tradeoff)."""
    if isinstance(node, ast.Assign):
        val = _classify(node.value, prov, imports)
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                prov[tgt.id] = val
    elif isinstance(node, ast.AnnAssign) and node.value is not None \
            and isinstance(node.target, ast.Name):
        prov[node.target.id] = _classify(node.value, prov, imports)
    elif isinstance(node, ast.AugAssign) and isinstance(
            node.target, ast.Name):
        prov[node.target.id] = max(
            prov.get(node.target.id, _DYNAMIC),
            _classify(node.value, prov, imports),
        )
    elif isinstance(node, ast.For) and isinstance(node.target, ast.Name):
        # iterating a bucket ladder yields bucketed values
        prov[node.target.id] = _classify(node.iter, prov, imports)


# ----------------------------------------------------------- entry point


def jitflow_findings(
    audits: Sequence[ModuleAudit],
    graph: CallGraph,
    roots: Dict[str, ThreadRoot],
) -> List[Finding]:
    """Run all five v5 families over already-collected audits."""
    inv = build_inventory(audits)
    if not inv.roots and not inv.factories:
        return []
    caller_held = lockset.caller_held_locks(audits, graph, roots)
    findings: List[Finding] = []
    findings.extend(_retrace_and_donation_findings(audits, inv))
    findings.extend(_host_sync_findings(audits, graph, inv))
    findings.extend(_dispatch_findings(audits, inv, caller_held))
    findings.extend(_tracer_findings(audits, graph, inv))
    return sorted(set(findings))


# --------------------------------- family 1 + 4: retrace and donation


def _retrace_and_donation_findings(
    audits: Sequence[ModuleAudit], inv: Inventory,
) -> List[Finding]:
    out: List[Finding] = []
    names = {r.name for r in inv.roots} | {f.name for f in inv.factories}
    for audit in audits:
        mod = audit.module
        if not any(n in mod.source for n in names):
            continue
        retrace_exempt = _is_exempt(mod.relpath)
        for fn in audit.functions:
            if fn.node is None:  # the <module> pseudo record
                continue
            visible = inv.visible_roots(fn.qual, audit.dotted)
            prov: Dict[str, int] = {}
            #: donated name → (donating line, root name); killed on
            #: re-assignment
            donated: Dict[str, Tuple[int, str]] = {}
            for node in _ordered_body(fn.node):
                _track_assign(node, prov, audit.imports)
                if isinstance(node, ast.Name):
                    self_donate = donated.get(node.id)
                    if self_donate is not None:
                        if isinstance(node.ctx, ast.Store):
                            del donated[node.id]
                        elif isinstance(node.ctx, ast.Load) \
                                and node.lineno > self_donate[0]:
                            line, rname = self_donate
                            del donated[node.id]
                            if _suppressed(mod, DONATION_RULE,
                                           node.lineno):
                                continue
                            out.append(_finding(
                                mod, DONATION_RULE, node.lineno,
                                f"`{node.id}` was donated to jitted "
                                f"`{rname}` (line {line}, donate_"
                                "argnums) — its device buffer now "
                                "belongs to XLA and this read sees "
                                "freed memory; re-fetch the value from "
                                "the call's outputs or drop the "
                                "donation",
                            ))
                    continue
                if not isinstance(node, ast.Call):
                    continue
                resolved = resolve_dotted(node.func, audit.imports)
                bare = (
                    node.func.id if isinstance(node.func, ast.Name)
                    else None
                )
                if not retrace_exempt:
                    factory = inv.factory_for(bare, resolved, audit.dotted)
                    if factory is not None and factory.qual != fn.qual:
                        out.extend(_check_factory_call(
                            mod, fn, node, factory, prov, audit.imports,
                        ))
                root = visible.get(bare) if bare else None
                if root is None:
                    root = inv.root_by_qual(resolved)
                if root is None:
                    continue
                if not retrace_exempt:
                    out.extend(_check_static_args(
                        mod, fn, node, root, prov, audit.imports,
                    ))
                _record_donations(node, root, donated)
    return out


def _check_factory_call(
    mod: Module, fn: FnAudit, call: ast.Call, factory: JitFactory,
    prov: Dict[str, int], imports: Dict[str, str],
) -> List[Finding]:
    out: List[Finding] = []
    for i, arg in enumerate(call.args):
        if _classify(arg, prov, imports) != _DYNAMIC:
            continue
        if _suppressed(mod, RETRACE_RULE, call.lineno):
            continue
        pname = (
            factory.params[i] if i < len(factory.params) else f"#{i}"
        )
        out.append(_finding(
            mod, RETRACE_RULE, call.lineno,
            f"jit factory `{factory.name}` called with dynamic geometry "
            f"argument `{pname}` — every distinct value is a separate "
            "XLA compile (seconds in the tick path); derive it from the "
            "bucket ladder (bucket_nodes/bucket_pools, or the "
            "snapshot's `.bucket`)",
        ))
    return out


def _check_static_args(
    mod: Module, fn: FnAudit, call: ast.Call, root: JitRoot,
    prov: Dict[str, int], imports: Dict[str, str],
) -> List[Finding]:
    out: List[Finding] = []
    flagged: List[Tuple[int, str]] = []
    for kw in call.keywords:
        if kw.arg in root.static_argnames and \
                _classify(kw.value, prov, imports) == _DYNAMIC:
            flagged.append((call.lineno, kw.arg))
    for idx in root.static_argnums:
        if idx < len(call.args) and \
                _classify(call.args[idx], prov, imports) == _DYNAMIC:
            flagged.append((call.lineno, f"#{idx}"))
    for line, which in flagged:
        if _suppressed(mod, RETRACE_RULE, line):
            continue
        out.append(_finding(
            mod, RETRACE_RULE, line,
            f"jitted `{root.name}` called with dynamic value for static "
            f"argument `{which}` — each distinct value retraces and "
            "recompiles; feed a bucket-ladder value "
            "(bucket_nodes/bucket_pools) or a module constant",
        ))
    return out


def _record_donations(
    call: ast.Call, root: JitRoot, donated: Dict[str, Tuple[int, str]],
) -> None:
    for idx in root.donate_argnums:
        if idx < len(call.args) and isinstance(call.args[idx], ast.Name):
            donated[call.args[idx].id] = (call.lineno, root.name)
    for kw in call.keywords:
        if kw.arg in root.donate_argnames and isinstance(
                kw.value, ast.Name):
            donated[kw.value.id] = (call.lineno, root.name)


# ------------------------------------- family 2: host sync in hot path


def _hot_set(audits: Sequence[ModuleAudit], graph: CallGraph) -> Set[str]:
    """Quals on the reconcile/scan/tick paths: call-graph closure of the
    hot root names, widened with nested defs of hot functions (a jit
    factory's inner ``run`` executes inside its caller's scan even
    though the factory-result call ``_tick_fn(nb, pb)(...)`` has no
    nominal edge), iterated to fixpoint."""
    hot: Set[str] = {
        fn.qual
        for audit in audits
        for fn in audit.functions
        if fn.name in _HOT_ROOT_NAMES
        and audit.module.relpath.startswith("tpu_cc_manager/")
        and not _is_exempt(audit.module.relpath)
    }
    all_quals = [
        fn.qual for audit in audits for fn in audit.functions
    ]
    while True:
        grown = graph.reachable(hot) | hot
        for q in all_quals:
            if q in grown:
                continue
            parent = q.rsplit(".", 1)[0]
            if parent in grown:
                grown.add(q)
        if grown == hot:
            return hot
        hot = grown


def _host_sync_findings(
    audits: Sequence[ModuleAudit], graph: CallGraph, inv: Inventory,
) -> List[Finding]:
    hot = _hot_set(audits, graph)
    out: List[Finding] = []
    for audit in audits:
        mod = audit.module
        if _is_exempt(mod.relpath):
            continue
        for fn in audit.functions:
            if fn.qual not in hot or fn.node is None:
                continue
            visible = inv.visible_roots(fn.qual, audit.dotted)
            #: locals holding raw (device-side) jit outputs
            jit_out: Set[str] = set()
            for node in _ordered_body(fn.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    tgt = node.targets[0].id
                    if _is_jit_output(node.value, visible, jit_out,
                                      audit.imports, inv, audit.dotted):
                        jit_out.add(tgt)
                    else:
                        jit_out.discard(tgt)
                    continue
                if isinstance(node, ast.For) and \
                        _derived_from(node.iter, jit_out):
                    _emit_sync(out, mod, fn, node.lineno,
                               "iterating a jitted output")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                line = node.lineno
                func = node.func
                resolved = resolve_dotted(func, audit.imports) or ""
                if (isinstance(func, ast.Attribute)
                        and func.attr == "block_until_ready") or \
                        resolved == "jax.block_until_ready":
                    _emit_sync(out, mod, fn, line,
                               "`block_until_ready()` parks the thread "
                               "until the device finishes")
                    continue
                if isinstance(func, ast.Attribute) and \
                        func.attr == "item" and \
                        _derived_from(func.value, jit_out):
                    _emit_sync(out, mod, fn, line,
                               "`.item()` on a jitted output")
                    continue
                if isinstance(func, ast.Name) and \
                        func.id in ("float", "int", "bool", "list") and \
                        node.args and _derived_from(node.args[0], jit_out):
                    _emit_sync(out, mod, fn, line,
                               f"`{func.id}()` on a jitted output")
                    continue
                if resolved.startswith("numpy.") and \
                        resolved.rsplit(".", 1)[-1] in (
                            "asarray", "array") and \
                        node.args and _derived_from(node.args[0], jit_out):
                    _emit_sync(out, mod, fn, line,
                               "`np.asarray()` on a jitted output")
    return out


def _emit_sync(
    out: List[Finding], mod: Module, fn: FnAudit, line: int, what: str,
) -> None:
    if _suppressed(mod, SYNC_RULE, line):
        return
    out.append(_finding(
        mod, SYNC_RULE, line,
        f"{what} inside `{fn.name}`, which is on a reconcile/scan hot "
        "path — an implicit device→host sync stalls the controller "
        "thread on device completion; batch transfers through one "
        "explicit jax.device_get at the dispatch boundary",
    ))


def _is_jit_output(
    value: ast.AST, visible: Dict[str, JitRoot], jit_out: Set[str],
    imports: Dict[str, str], inv: Inventory, moddot: str,
) -> bool:
    """Whether an assigned RHS is a raw (device-side) jitted output.
    ``jax.device_get(...)`` results are host-side by definition; a jit
    FACTORY's result is the host-facing wrapper it returns, not a
    device value."""
    if isinstance(value, ast.Subscript):
        return _derived_from(value.value, jit_out)
    if not isinstance(value, ast.Call):
        return False
    func = value.func
    if isinstance(func, ast.Name):
        if func.id in visible:
            return True
        if func.id in jit_out:
            return False
    resolved = resolve_dotted(func, imports)
    root = inv.root_by_qual(resolved)
    return root is not None


def _derived_from(expr: ast.AST, jit_out: Set[str]) -> bool:
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    return isinstance(expr, ast.Name) and expr.id in jit_out


# ------------------------------------ family 3: unserialized dispatch


def _dispatch_findings(
    audits: Sequence[ModuleAudit], inv: Inventory,
    caller_held: Dict[str, FrozenSet[str]],
) -> List[Finding]:
    """Every call site of a collective (shard_map-wrapped) jitted
    callable must hold ``_DISPATCH_LOCK`` — lexically or on every
    resolved call path in (the caller-held ⋂-fixpoint)."""
    collective = [r for r in inv.roots if r.collective]
    if not collective:
        return []
    out: List[Finding] = []
    for audit in audits:
        mod = audit.module
        for fn in audit.functions:
            visible = {
                name: root
                for name, root in inv.visible_roots(
                    fn.qual, audit.dotted).items()
                if root.collective
            }
            if not visible:
                continue
            inherited = caller_held.get(fn.qual, frozenset())
            for c in fn.calls:
                if c.bare is None or c.bare not in visible:
                    continue
                held = c.held_locks | inherited
                if any(
                    q.rsplit(".", 1)[-1] == _DISPATCH_LOCK_NAME
                    for q in held
                ):
                    continue
                if _suppressed(mod, DISPATCH_RULE, c.line):
                    continue
                out.append(_finding(
                    mod, DISPATCH_RULE, c.line,
                    f"collective jitted `{c.bare}` dispatched without "
                    f"holding {_DISPATCH_LOCK_NAME} (plan.py's dispatch "
                    "contract): XLA's cross-module all-reduce "
                    "rendezvous must not interleave from multiple host "
                    "threads — concurrent dispatch parks participants "
                    "in multi-second stalls; wrap the call in `with "
                    "plan._DISPATCH_LOCK:` or route through the "
                    "factory's locked runner",
                ))
    return out


# ----------------------------------------------- family 5: tracer leak


def _tracer_findings(
    audits: Sequence[ModuleAudit], graph: CallGraph, inv: Inventory,
) -> List[Finding]:
    targets = {r.target for r in inv.roots if r.target}
    if not targets:
        return []
    #: static names per traced target (a condition on a static arg is
    #: Python-level: it re-traces, by design, rather than failing)
    static_of: Dict[str, Set[str]] = {}
    for r in inv.roots:
        if r.target:
            static_of.setdefault(r.target, set()).update(
                r.static_argnames)
    traced = graph.reachable(targets) | targets
    by_qual: Dict[str, Tuple[ModuleAudit, FnAudit]] = {
        fn.qual: (audit, fn)
        for audit in audits for fn in audit.functions
    }
    out: List[Finding] = []
    for qual in sorted(traced):
        hit = by_qual.get(qual)
        if hit is None:
            continue
        audit, fn = hit
        mod = audit.module
        if _is_exempt(mod.relpath):
            continue
        for a in fn.accesses:
            if a.kind != "write" or a.init:
                continue
            if _suppressed(mod, TRACER_RULE, a.line):
                continue
            where = (
                f"module global `{a.key[1]}`" if a.key[0] == "global"
                else f"attribute `self.{a.key[-1]}`"
            )
            out.append(_finding(
                mod, TRACER_RULE, a.line,
                f"write to {where} inside `{fn.name}`, which runs under "
                "a jax trace: the statement executes once per "
                "(re)trace, not once per call — the stored value is a "
                "tracer or a stale trace-time constant; return it from "
                "the kernel instead",
            ))
        if qual in targets:
            out.extend(_tracer_condition_findings(
                mod, fn, static_of.get(qual, set())))
    return out


def _tracer_condition_findings(
    mod: Module, fn: FnAudit, static_names: Set[str],
) -> List[Finding]:
    """``if``/``while`` on a positional (traced-array) parameter inside
    a direct jit target: TracerBoolConversionError at trace time.
    Keyword-only parameters are config, not arrays; ``is (not) None``
    and ``isinstance`` are Python-level defaulting."""
    array_params = {
        p for p in fn.params if p not in ("self", "cls")
    } - static_names
    kwonly = {
        a.arg for a in getattr(fn.node.args, "kwonlyargs", [])
    }
    array_params -= kwonly
    if not array_params:
        return []
    out: List[Finding] = []
    for node in _ordered_body(fn.node):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        test = node.test
        if _is_python_level_test(test):
            continue
        used = {
            n.id for n in ast.walk(test)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        } & array_params
        if not used or _suppressed(mod, TRACER_RULE, node.lineno):
            continue
        name = sorted(used)[0]
        out.append(_finding(
            mod, TRACER_RULE, node.lineno,
            f"Python `{type(node).__name__.lower()}` on traced "
            f"parameter `{name}` inside jitted `{fn.name}` — a tracer "
            "has no truth value (TracerBoolConversionError); use "
            "jnp.where/lax.cond, or declare the argument static",
        ))
    return out


def _is_python_level_test(test: ast.AST) -> bool:
    if isinstance(test, ast.BoolOp):
        return all(_is_python_level_test(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_python_level_test(test.operand)
    if isinstance(test, ast.Compare):
        return all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        )
    if isinstance(test, ast.Call):
        term = test.func.attr if isinstance(test.func, ast.Attribute) \
            else test.func.id if isinstance(test.func, ast.Name) else None
        return term in ("isinstance", "callable", "hasattr")
    return False
