"""ccaudit baseline: the ratchet that lets findings only burn down.

The committed ``baseline.json`` records the findings the project has
consciously decided to live with, each pinned to (rule, file, line,
stripped source text). The gate then has two failure modes, both fatal:

- a **new** finding (not in the baseline) — the change introduced a
  violation; fix it or pragma it with a reason;
- a **stale** entry (in the baseline but no longer matching a current
  finding) — the code it suppressed moved or was fixed, so the entry
  must be deleted (``--write-baseline`` regenerates). Pinning to line
  *and* text means an entry can't silently slide onto different code
  and mask a fresh regression — the same freshness discipline the
  scenario and kustomize trees get from their gating tests.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import List, Optional, Sequence, Tuple

from tpu_cc_manager.analysis.core import Finding, repo_root

#: Repo-relative path of the committed baseline.
BASELINE_PATH = "tpu_cc_manager/analysis/baseline.json"

_VERSION = 1


def load_baseline(path: Optional[str] = None) -> List[dict]:
    path = path or os.path.join(repo_root(), BASELINE_PATH)
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != _VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} in {path}"
        )
    return data.get("findings", [])


def write_baseline(
    findings: Sequence[Finding], path: Optional[str] = None
) -> None:
    path = path or os.path.join(repo_root(), BASELINE_PATH)
    payload = {
        "version": _VERSION,
        "findings": [f.to_json() for f in sorted(findings)],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def _entry_key(entry: dict) -> Tuple[str, str, int, str]:
    return (
        entry.get("rule", ""),
        entry.get("file", ""),
        int(entry.get("line", 0)),
        entry.get("text", ""),
    )


def diff_against_baseline(
    findings: Sequence[Finding], entries: Sequence[dict]
) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """(new, suppressed, stale): findings absent from the baseline, findings
    the baseline covers, and baseline entries matching nothing current.

    Multiset semantics: two identical-key violations on one source line
    are two findings, and one baseline entry suppresses exactly one of
    them — a single entry can't silently blanket a line."""
    remaining = Counter(_entry_key(e) for e in entries)
    new: List[Finding] = []
    suppressed: List[Finding] = []
    for f in sorted(findings):
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            suppressed.append(f)
        else:
            new.append(f)
    current = Counter(f.key() for f in findings)
    seen: Counter = Counter()
    stale = []
    for e in entries:
        k = _entry_key(e)
        seen[k] += 1
        if seen[k] > current.get(k, 0):
            stale.append(e)
    return new, suppressed, stale
