"""Label / annotation keys — the cluster-visible protocol surface.

The reference coordinates the whole fleet exclusively through node labels
(SURVEY.md §2.3): a desired-state label written by the operator, an
observed-state label written by the agent, and component pause labels used
to drain the GPU operator's pods. This module is the TPU-native rename of
that protocol; everything else in the framework refers to these constants.
"""

from __future__ import annotations

#: Desired-state label (analog of ``nvidia.com/cc.mode``, reference
#: cmd/main.go:39, main.py:50).
CC_MODE_LABEL = "tpu.google.com/cc.mode"

#: Observed-state label (analog of ``nvidia.com/cc.mode.state``, reference
#: gpu_operator_eviction.py:279). Value: the achieved mode, or "failed".
CC_MODE_STATE_LABEL = "tpu.google.com/cc.mode.state"

#: Pause-label protocol for TPU-stack components (analog of the five
#: ``nvidia.com/gpu.deploy.*`` labels, reference
#: gpu_operator_eviction.py:23-29). A cooperating operator's DaemonSets
#: carry nodeAffinity on these labels; setting the value to
#: ``paused-for-cc-flip`` (with the original value preserved as a suffix)
#: makes the operator remove the pod from the node.
COMPONENT_LABELS = (
    "tpu.google.com/pool.deploy.device-plugin",
    "tpu.google.com/pool.deploy.metrics-exporter",
    "tpu.google.com/pool.deploy.dra-driver",
    "tpu.google.com/pool.deploy.workload-validator",
    "tpu.google.com/pool.deploy.node-problem-detector",
)

#: App labels identifying the pods of each component above (analog of
#: ``COMPONENT_APP_LABELS``, reference gpu_operator_eviction.py:32-38).
COMPONENT_APP_LABELS = {
    "tpu.google.com/pool.deploy.device-plugin": "tpu-device-plugin",
    "tpu.google.com/pool.deploy.metrics-exporter": "tpu-metrics-exporter",
    "tpu.google.com/pool.deploy.dra-driver": "tpu-dra-driver",
    "tpu.google.com/pool.deploy.workload-validator": "tpu-workload-validator",
    "tpu.google.com/pool.deploy.node-problem-detector": "tpu-node-problem-detector",
}

#: Pause marker prefix (analog of ``PAUSED_STR = "paused-for-cc-flip"``,
#: reference gpu_operator_eviction.py:40-70).
PAUSED_STR = "paused-for-cc-flip"

#: Label selecting TPU nodes (set by GKE on TPU node pools); the DaemonSet
#: nodeSelector keys on it, and the fleet controller uses it to scope
#: listings.
TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"

#: GKE labels giving slice identity/topology on multi-host TPU node pools.
#: All nodes of one multi-host slice share the same topology value and
#: belong to one node pool; per-slice coherence keys off these.
TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"  # ccaudit: allow-protocol-liveness(GKE-written; documented for operators — slice identity keys off TPU_SLICE_LABEL)
TPU_SLICE_LABEL = "tpu.google.com/cc.slice"

#: Slice-coordination annotations (new vs the reference — SURVEY.md §7.2
#: step 7). See tpu_cc_manager.slice_coord for the protocol.
SLICE_LEADER_ANNOTATION = "tpu.google.com/cc.slice.leader"  # ccaudit: allow-protocol-liveness(operator-facing breadcrumb: leadership is recomputed from the member list, never read back)
SLICE_EPOCH_ANNOTATION = "tpu.google.com/cc.slice.epoch"
SLICE_ACK_ANNOTATION = "tpu.google.com/cc.slice.ack"
SLICE_COMMIT_ANNOTATION = "tpu.google.com/cc.slice.commit"
SLICE_HB_ANNOTATION = "tpu.google.com/cc.slice.hb"
SLICE_DONE_ANNOTATION = "tpu.google.com/cc.slice.done"

#: Per-flip attestation evidence annotation (tpu_cc_manager.evidence):
#: a hashed/HMAC'd document binding node, live device identities,
#: independently-read effective modes, and a statefile digest. Written by
#: the agent at every successful reconcile; audited fleet-wide by the
#: fleet controller.
EVIDENCE_ANNOTATION = "tpu.google.com/cc.evidence"

#: Node-local doctor verdict (tpu_cc_manager.doctor --publish): a
#: compact {ok, fail[], warn[]} summary of the node's trust-surface
#: checks, aggregated fleet-wide by the fleet controller — the
#: "deep-scan" channel that doesn't trust labels because it is produced
#: by the same cross-checks that catch lying labels.
DOCTOR_ANNOTATION = "tpu.google.com/cc.doctor"

#: Selectable mirror of the doctor verdict ("true"/"false"): label
#: selectors can't see annotations, and operators need
#: ``kubectl get nodes -l tpu.google.com/cc.doctor.ok=false`` to find
#: the nodes failing trust-surface checks without parsing JSON.
DOCTOR_OK_LABEL = "tpu.google.com/cc.doctor.ok"

#: Durable rollout record (tpu_cc_manager.rollout): the group plan,
#: per-group outcomes, and budget of the pool's current/last rollout,
#: stored as an annotation on the pool's anchor node so an operator-side
#: crash mid-rollout can be resumed (`rollout --resume`) from cluster
#: state alone.
ROLLOUT_ANNOTATION = "tpu.google.com/cc.rollout"

#: Cross-process trace context (tpu_cc_manager.trace, ISSUE 8): a
#: W3C-traceparent-style string ("00-<trace>-<span>-01") stamped by
#: whoever WRITES the desired-mode label — the rollout driver, the
#: policy controller, or the simlab driver — in the SAME node write as
#: the label itself (zero extra round trips). The agent's watch
#: surfaces it and the reconcile adopts it, so one trace id spans
#: desired-write → watch delivery → flip → state publish across
#: process boundaries. Observability only: never parsed for control
#: decisions, and a missing/garbled value degrades to a local trace.
CC_TRACE_ANNOTATION = "tpu.google.com/cc.trace"

#: Node taint held for the duration of a mode flip so the *scheduler* —
#: not just the component pause labels — keeps new TPU work off a node
#: whose devices are gated mid-flip. Cleared when the flip cycle ends
#: (success or failure; the cc.mode.state label carries the outcome).
FLIP_TAINT_KEY = "tpu.google.com/cc.mode"
FLIP_TAINT_VALUE = "flipping"
FLIP_TAINT_EFFECT = "NoSchedule"

#: Pod-side request for a confidential-compute guarantee
#: (tpu_cc_manager.webhook): a pod carrying this label asks to run only
#: on nodes whose OBSERVED mode (cc.mode.state — the agent-published
#: truth, not the desired label) equals the value. The mutating webhook
#: injects the matching nodeSelector; the validating webhook rejects
#: specs that contradict it (wrong explicit selector, or tolerating the
#: flip taint, which would let the pod land mid-flip).
REQUIRES_CC_LABEL = "tpu.google.com/requires-cc-mode"

#: Agent code-version breadcrumb (simlab's rolling-upgrade drill, and
#: any future agent that wants to advertise its build): written by the
#: reconcile path as a deferred publication riding a carrier write
#: (zero extra round trips), read by operators and by simlab's
#: lifecycle invariants oracle to prove a rolling agent upgrade
#: completed on every cohort.
AGENT_VERSION_ANNOTATION = "tpu.google.com/cc.agent-version"

#: TPUCCPolicy custom resource (tpu_cc_manager.policy): the declarative,
#: level-triggered replacement for hand-run rollouts. Cluster-scoped —
#: a policy selects node pools by label selector, so namespacing it
#: would be a lie. The reference has no declarative surface at all
#: (admins patch labels by hand, reference README_PYTHON.md:77-102).
POLICY_GROUP = "tpu.google.com"
POLICY_VERSION = "v1alpha1"
POLICY_PLURAL = "tpuccpolicies"
POLICY_KIND = "TPUCCPolicy"
