"""CLI entrypoint: ``python -m tpu_cc_manager``.

Modes of operation (parity with both reference CLIs):

- no subcommand: run the long-lived agent (reference main.py:703-759,
  cmd/main.go:78-117);
- ``set-cc-mode -m <mode>``: one-shot engine invocation, the bash-engine
  CLI surface (reference scripts/cc-manager.sh:472-533) — this is also
  what the native C++ agent execs per reconcile;
- ``get-cc-mode``: print per-device modes as JSON;
- ``rollout -m <mode>``: operator-side rolling mode change across the
  pool (new vs the reference — see tpu_cc_manager.rollout);
- ``fleet-controller``: long-running read-only fleet audit service
  (JAX fleet scans served as /metrics + /report — see
  tpu_cc_manager.fleet);
- ``policy-controller``: declarative TPUCCPolicy reconciler — drives
  bounded rollouts toward the modes the cluster's policy objects
  declare (see tpu_cc_manager.policy);
- ``webhook``: admission webhook steering requires-cc pods onto
  verified nodes and rejecting contradictory specs (see
  tpu_cc_manager.webhook);
- ``doctor``: node-local trust-surface diagnostic — statefile, gate,
  holders, labels, evidence cross-checked in one JSON report (see
  tpu_cc_manager.doctor);
- ``simlab``: the fleet-scale scenario lab — hundreds of live
  reconciling replicas, scripted faults, JSON artifacts (see
  tpu_cc_manager.simlab, docs/simlab.md).
"""

from __future__ import annotations

import json
import logging
import os
import sys

from tpu_cc_manager import labels as L
from tpu_cc_manager.agent import CCManagerAgent
from tpu_cc_manager.config import parse_config
from tpu_cc_manager.drain import build_drainer, set_cc_mode_state_label
from tpu_cc_manager.engine import FatalModeError, ModeEngine, NullDrainer
from tpu_cc_manager.k8s.client import (
    ApiException, HttpKubeClient, KubeConfig,
)
from tpu_cc_manager.obs import setup_logging

log = logging.getLogger("tpu-cc-manager")


def _kube_client(cfg):
    config = KubeConfig.load(cfg.kubeconfig)
    if os.environ.get("TPU_CC_KUBE_AIO", "").lower() in ("1", "true",
                                                         "yes"):
        # the asyncio I/O core (ISSUE 13, docs/io.md §async core): all
        # of this process's node reads/writes/watches multiplex one
        # event loop's pipelined connection pool behind a sync façade.
        # Opt-in: exec-credential (401 invalidate-and-retry) auth flows
        # are not implemented there and must stay on HttpKubeClient.
        from tpu_cc_manager.k8s.aio_bridge import SyncKubeFacade

        return SyncKubeFacade(config)
    return HttpKubeClient(config)


def _leader_elector(kube, lease_name: str):
    """LeaderElector for a controller, when TPU_CC_LEADER_ELECT=true
    (manifests set it; single-replica/dev runs skip election). Identity
    is the pod name (downward API) so `kubectl get lease` names the
    actual holder pod."""
    from tpu_cc_manager.config import _env_bool

    if not _env_bool("TPU_CC_LEADER_ELECT", False):
        return None
    import socket

    from tpu_cc_manager.leader import LeaderElector

    identity = (
        os.environ.get("POD_NAME")
        or f"{socket.gethostname()}-{os.getpid()}"
    )
    from tpu_cc_manager.k8s.client import HttpKubeClient

    if isinstance(kube, HttpKubeClient):
        # the elector gets its OWN unlimited client: lease renewals
        # must never queue behind flow-controlled scan/rollout traffic
        # (a renew delayed past the lease duration self-demotes the
        # leader mid-rollout — the classic client-go shared-limiter
        # footgun). Lease traffic is one GET+PUT per renew interval;
        # unlimited is safe by construction.
        kube = HttpKubeClient(kube.config, qps=0)
    return LeaderElector(
        kube,
        name=lease_name,
        identity=identity,
        namespace=os.environ.get("OPERATOR_NAMESPACE", "tpu-system"),
    )


def _stop_on_sigterm(stop_fn) -> None:
    """Make SIGTERM (the kubelet's pod-stop signal) a clean shutdown for
    long-running commands, like the C++ agent's on_signal
    (native/agent.cpp) and the bash engine's traps. The stop runs on a
    helper thread: a handler calling it inline could re-enter a lock
    the interrupted main thread already holds."""
    import signal
    import threading

    def handler(signum, frame):
        threading.Thread(
            target=stop_fn, daemon=True, name="sigterm-stop"
        ).start()

    try:
        signal.signal(signal.SIGTERM, handler)
    except ValueError:
        pass  # not the main thread (embedded use): skip


def main(argv=None) -> int:
    cfg, args = parse_config(argv)
    setup_logging(cfg.debug, fmt=cfg.log_format)

    if args.command == "probe-devices":
        # Device inventory; --backend jax (default) asks the live TPU
        # runtime — the hardware-truth surface (reference main.py:258-296
        # queries hardware the same way). Never tracebacks: failures come
        # back as JSON with rc 1.
        import os as _os

        from tpu_cc_manager.device import describe_backend
        from tpu_cc_manager.device.base import _default_backend

        # scope the backend override to this call — main() also runs
        # in-process (tests, embedders), where a permanent os.environ
        # mutation would silently re-route every later backend default
        prev = _os.environ.get("TPU_CC_DEVICE_BACKEND")
        _os.environ["TPU_CC_DEVICE_BACKEND"] = args.backend
        try:
            out = describe_backend(_default_backend(), name=args.backend)
        except Exception as e:
            print(json.dumps(
                {"backend": args.backend, "error": str(e), "devices": []},
                indent=2, sort_keys=True,
            ))
            return 1
        finally:
            if prev is None:
                _os.environ.pop("TPU_CC_DEVICE_BACKEND", None)
            else:
                _os.environ["TPU_CC_DEVICE_BACKEND"] = prev
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0

    if args.command == "get-cc-mode":
        engine = ModeEngine(set_state_label=lambda v: None, drainer=NullDrainer(),
                            evict_components=False)
        print(json.dumps(engine.get_modes(), indent=2, sort_keys=True))
        return 0

    if args.command == "rollout":
        from tpu_cc_manager.modes import InvalidModeError, parse_mode
        from tpu_cc_manager.rollout import Rollout, RolloutError

        # the judge's event feed (ISSUE 14): one LIST-then-WATCH
        # informer so steady-state group judging reads local memory
        # instead of re-LISTing the pool every poll tick. A client
        # without watch support degrades inside the informer, and the
        # rollout's liveness fallback then pays its own interval LISTs
        # — the historical behavior. Dry runs never judge, so they
        # skip the stream entirely. The informer is cluster-wide (the
        # cache layer has no selector scoping, and label-selector
        # watches aren't in this client): for a rollout outliving a
        # few poll ticks that is still LESS API load than the scoped
        # LIST-per-tick it replaces, but a seconds-long rollout of a
        # handful of nodes in a huge mixed cluster pays a fleet-wide
        # prime for it.
        # argument validation BEFORE any API traffic: a usage error
        # must not cost a fleet-wide informer prime
        if args.resume:
            if args.mode:
                log.error("--resume takes the mode from the durable "
                          "record; do not pass --mode")
                return 1
            if (args.max_unavailable != 1 or args.failure_budget != 0
                    or args.canary != 0):
                log.error("--resume takes the window, budget, and "
                          "canary from the durable record; do not "
                          "pass --max-unavailable/--failure-budget/"
                          "--canary")
                return 1
        elif not args.mode:
            log.error("rollout requires -m/--mode (or --resume)")
            return 1
        else:
            try:
                parse_mode(args.mode)
            except InvalidModeError as e:
                log.error("rollout refused: %s", e)
                return 1
        informer = None
        kube = _kube_client(cfg)
        if not args.dry_run:
            from tpu_cc_manager.watch import NodeInformer

            try:
                informer = NodeInformer(kube, name="rollout")
                informer.prime()
                informer.start()
            except Exception as e:
                log.warning("node informer unavailable (%s); judging "
                            "on the poll interval", e)
                informer = None
        try:
            if args.resume:
                rollout = Rollout.resume(
                    kube,
                    selector=args.selector,
                    group_timeout_s=args.group_timeout,
                    dry_run=args.dry_run,
                    verify_evidence=not args.no_verify_evidence,
                    informer=informer,
                )
            else:
                rollout = Rollout(
                    kube,
                    args.mode,
                    selector=args.selector or L.TPU_ACCELERATOR_LABEL,
                    max_unavailable=args.max_unavailable,
                    failure_budget=args.failure_budget,
                    canary=args.canary,
                    group_timeout_s=args.group_timeout,
                    force=args.force,
                    dry_run=args.dry_run,
                    verify_evidence=not args.no_verify_evidence,
                    informer=informer,
                )
            report = rollout.run()
        except (InvalidModeError, RolloutError) as e:
            log.error("rollout refused: %s", e)
            return 1
        finally:
            if informer is not None:
                informer.stop()
        print(report.to_json())
        return 0 if report.ok else 1

    if args.command == "fleet-controller":
        from tpu_cc_manager.fleet import FleetController

        # production default: warm the planner's AOT compile cache at
        # start (the restarted-controller-in-milliseconds contract,
        # docs/planner.md). --once audits and in-process embedders skip
        # it; TPU_CC_PLANNER_WARMUP=0 opts a long-running controller out
        if not args.once:
            os.environ.setdefault("TPU_CC_PLANNER_WARMUP", "1")
        try:
            kube = _kube_client(cfg)
            controller = FleetController(
                kube,
                selector=args.selector,
                interval_s=args.interval,
                port=args.port,
                leader_elector=_leader_elector(
                    kube, "tpu-cc-fleet-controller"
                ),
            )
            if args.once:
                # cron/CI audit: one scan, report on stdout, exit code
                # says whether the fleet has problems an operator must
                # look at
                report = controller.scan_once()
                # problems INSIDE the printed JSON (scan_once computes
                # them for the live /report too): a CI consumer gets
                # the actionable lines from stdout, not just the exit
                # code (stderr logging kept for humans watching cron)
                problems = report["problems"]
                print(json.dumps(report, indent=2, sort_keys=True))
                if problems:
                    log.error("fleet audit found problems: %s", problems)
                return 1 if problems else 0
            _stop_on_sigterm(controller.stop)
            # OSError belongs inside the guard too: RouteServer binds
            # lazily in run(), so a busy --port surfaces here
            return controller.run()
        except (ValueError, OSError, ApiException) as e:
            log.error("fleet-controller refused: %s", e)
            return 1

    if args.command == "policy-controller":
        from tpu_cc_manager.policy import PolicyController

        # same production default as fleet-controller: the policy scan
        # dispatches the jitted planner kernel (plan.analyze_pools)
        os.environ.setdefault("TPU_CC_PLANNER_WARMUP", "1")
        try:
            kube = _kube_client(cfg)
            controller = PolicyController(
                kube,
                interval_s=args.interval,
                port=args.port,
                verify_evidence=not args.no_verify_evidence,
                leader_elector=_leader_elector(
                    kube, "tpu-cc-policy-controller"
                ),
            )
            if args.once:
                # cron/CI mode: one pass, report on stdout, exit code
                # says whether every policy is in a healthy phase
                report = controller.scan_once()
                # the actionable list rides INSIDE the printed JSON
                # (scan_once computes it for the live /report too) so
                # CI consumers read stdout, not stderr + exit code
                bad = report["unhealthy_policies"]
                print(json.dumps(report, indent=2, sort_keys=True))
                if report.get("crd_missing"):
                    # the long-running controller rides this out (next
                    # tick retries) — a one-shot has no next tick, and a
                    # green exit against a cluster where nothing can be
                    # reconciled would lie to the pipeline
                    log.error("TPUCCPolicy CRD not installed (or wrong "
                              "cluster): nothing was reconciled")
                    return 1
                if bad:
                    log.error("unhealthy policies: %s", bad)
                return 1 if bad else 0
            _stop_on_sigterm(controller.stop)
            return controller.run()
        except (ValueError, OSError, ApiException) as e:
            log.error("policy-controller refused: %s", e)
            return 1

    if args.command == "doctor":
        from tpu_cc_manager.doctor import main_from_args

        return main_from_args(cfg, args)

    if args.command == "simlab":
        from tpu_cc_manager.simlab import main_from_args

        return main_from_args(args)

    if args.command == "webhook":
        from tpu_cc_manager.webhook import AdmissionServer

        try:
            server = AdmissionServer(
                args.port, cert_file=args.cert, key_file=args.key,
            )
        except (ValueError, OSError) as e:
            log.error("webhook refused: %s", e)
            return 1
        _stop_on_sigterm(server.stop)
        return server.serve_forever()

    if args.command == "set-cc-mode":
        import time as _time
        import uuid as _uuid

        from tpu_cc_manager.drain import (
            build_reconcile_event, post_event_best_effort,
        )
        from tpu_cc_manager.modes import STATE_FAILED, InvalidModeError

        kube = _kube_client(cfg)
        from tpu_cc_manager.drain import NodeFlipTaint
        engine = ModeEngine(
            set_state_label=lambda v: set_cc_mode_state_label(
                kube, cfg.node_name, v
            ),
            drainer=build_drainer(kube, cfg),
            evict_components=cfg.evict_components and cfg.drain_strategy != "none",
            flip_taint=NodeFlipTaint(kube, cfg.node_name),
        )

        def _post_event(outcome: str, dur: float) -> None:
            # same best-effort visibility as the agent / bash engine
            if not cfg.emit_events:
                return
            event = build_reconcile_event(
                cfg.node_name, args.mode, outcome, dur,
                name=(
                    f"{cfg.node_name}.cc-oneshot."
                    f"{_uuid.uuid4().hex[:8]}"
                ),
            )
            if event is None:
                return
            post_event_best_effort(kube, event)

        # slice-coherent one-shot (SLICE_COORDINATION=true): the bash
        # engine delegates slice-labeled nodes here, so the native
        # agent path runs the SAME quorum protocol as the Python agent
        # instead of flipping slice members unilaterally (the
        # half-flipped-slice hole, VERDICT r3 missing #2). Uses the
        # identical coordinator + engine pairing as agent.reconcile.
        from tpu_cc_manager.slice_coord import (
            SliceAbortError, SliceCoordinator,
        )

        coordinator = None
        if cfg.slice_coordination:
            coordinator = SliceCoordinator(
                kube, cfg.node_name,
                commit_timeout_s=cfg.slice_commit_timeout_s,
            )

        t0 = _time.monotonic()
        try:
            if coordinator is not None:
                try:
                    coordinator.start()  # heartbeat, like the agent
                    ok = coordinator.apply_slice_coherent(
                        args.mode, engine
                    )
                finally:
                    coordinator.stop()
            else:
                ok = engine.set_mode(args.mode)
            if ok and cfg.emit_evidence:
                # same per-flip evidence the long-lived agent publishes
                from tpu_cc_manager.evidence import publish_evidence

                publish_evidence(kube, cfg.node_name)
            _post_event("success" if ok else "failure",
                        _time.monotonic() - t0)
            return 0 if ok else 1
        except InvalidModeError as e:
            # agent-path parity (agent.py reconcile): a typo'd mode is a
            # clean rejection (CCModeInvalid), not a flip failure
            log.error("rejecting desired mode: %s", e)
            try:
                set_cc_mode_state_label(kube, cfg.node_name, STATE_FAILED)
            except Exception as pub_err:
                log.error(
                    "could not publish cc.mode.state=failed: %s", pub_err
                )
            _post_event("invalid", _time.monotonic() - t0)
            return 1
        except SliceAbortError as e:
            # the slice never agreed; local devices untouched. Agent
            # parity (agent.py reconcile slice_abort path): publish the
            # failed state label — it is the cluster's only machine-
            # readable outcome for a one-shot run — then the Warning
            # event. (Shutdown/superseded variants don't apply to a
            # one-shot: there is no mailbox holding a newer mode.)
            log.error("slice coordination aborted: %s", e)
            try:
                set_cc_mode_state_label(kube, cfg.node_name, STATE_FAILED)
            except Exception as pub_err:
                log.error(
                    "could not publish cc.mode.state=failed: %s", pub_err
                )
            _post_event("slice_abort", _time.monotonic() - t0)
            return 1
        except FatalModeError as e:
            log.error("fatal: %s", e)
            _post_event("fatal", _time.monotonic() - t0)
            return 1
        except Exception:
            # Never exit without publishing failure: the state label is the
            # cluster's only machine-readable outcome for a one-shot run
            # (reference main.py:300-307). Best-effort — the label write
            # itself may be what failed.
            log.exception("set-cc-mode failed unexpectedly")
            try:
                set_cc_mode_state_label(kube, cfg.node_name, STATE_FAILED)
            except Exception as pub_err:
                log.error(
                    "could not publish cc.mode.state=failed: %s", pub_err
                )
            _post_event("error", _time.monotonic() - t0)
            return 1

    # long-lived agent
    kube = _kube_client(cfg)
    slice_coordinator = None
    if cfg.slice_coordination:
        from tpu_cc_manager.slice_coord import SliceCoordinator

        slice_coordinator = SliceCoordinator(
            kube, cfg.node_name,
            commit_timeout_s=cfg.slice_commit_timeout_s,
        )
    agent = CCManagerAgent(kube, cfg, slice_coordinator=slice_coordinator)
    _stop_on_sigterm(agent.shutdown)
    # the black box survives the kill: the SIGTERM dump runs first,
    # then chains into the clean-shutdown handler installed above
    from tpu_cc_manager.flightrec import install_sigterm_dump

    install_sigterm_dump(agent.flightrec)
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())
