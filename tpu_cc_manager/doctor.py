"""``doctor`` — node-local trust-surface diagnostic.

The framework's enforcement story spans several independent surfaces:
the durable staged/effective statefile, the device-node permission
gate, the exclusive-hold contract, the cluster labels, and the
attestation evidence. Each is self-healing in its own loop, but when an
operator is staring at a misbehaving node they need ONE command that
cross-checks all of them and says which link is broken. ``python -m
tpu_cc_manager doctor`` prints a JSON report of named checks, each
``ok`` / ``warn`` / ``fail``, and exits non-zero iff any check failed.

The reference has nothing like this — its debugging story is reading
the pod log of a `set -x` bash script (SURVEY.md §5.1).

Checks (device-local, always):

- ``enumerate``          — the backend can list devices at all;
- ``staged-committed``   — no device has a staged mode pending over its
  effective one (an interrupted flip that never reached commit);
- ``independent-read``   — the effective mode read through the OTHER
  implementation's store handle agrees (the engine's non-tautological
  verify surface);
- ``gate-perms``         — device-node permission bits encode the
  effective CC mode (flip-locked nodes are a ``warn``: that is the
  fail-secure hold, not drift);
- ``holders``            — foreign processes holding the device node
  (``warn``: legitimate workloads hold the chip between flips).

Checks (cluster, when the API server and NODE_NAME are available;
skipped with a ``warn`` otherwise):

- ``state-label``        — ``cc.mode.state`` matches the device-derived
  node mode (a mismatch is the lying-label case the evidence audit
  exists for — here caught on the node itself);
- ``desired-converged``  — desired label matches observed (divergence
  is a ``warn``: the agent may simply still be working);
- ``evidence``           — the published evidence annotation verifies,
  matches the local statefiles, and attests the labeled mode;
- ``identity``           — the evidence's platform-identity token
  verifies and binds to THIS node (``fail`` on a foreign/invalid
  token; ``warn`` when an explicitly configured provider produced no
  token, the token is expired, or the signature is unverifiable here);
- ``flip-taint``         — no leftover flip taint outside a flip.
"""

from __future__ import annotations

import json
import logging
from typing import List, Optional

from tpu_cc_manager import labels as L

log = logging.getLogger("tpu-cc-manager.doctor")


def _check(checks: List[dict], name: str, severity: str, detail: str) -> None:
    checks.append({"name": name, "severity": severity, "detail": detail})


def _node_mode_from_devices(chips, store) -> Optional[str]:
    """Device-derived node-level mode — delegates the derivation rules
    (ici precedence, 'mixed' on disagreement) to evidence_mode so the
    doctor's state-label check can never drift from what the published
    evidence attests."""
    from tpu_cc_manager.device.statefile import independent_read
    from tpu_cc_manager.evidence import evidence_mode

    devices = []
    for c in chips:
        entry = {"cc": None, "ici": None}
        if getattr(c, "is_cc_query_supported", False):
            entry["cc"] = (independent_read(store, c.path, "cc")
                           if store is not None else c.query_cc_mode())
        if getattr(c, "is_ici_query_supported", False):
            entry["ici"] = (independent_read(store, c.path, "ici")
                            if store is not None else c.query_ici_mode())
        devices.append(entry)
    return evidence_mode({"devices": devices})


def _identity_check(checks: List[dict], doc: dict,
                    node_name: str) -> None:
    """The node diagnoses its OWN identity posture, so a broken
    metadata path / lapsed token / foreign token surfaces here first,
    not as a fleet-wide audit finding. Reuses the already-parsed
    evidence document; never dials the metadata server (that would add
    a blocking probe to every doctor run) — the provider MODE comes
    from the env alone."""
    import os as _os

    from tpu_cc_manager.identity import judge_identity

    iverdict, idetail = judge_identity(doc, node_name)
    mode = _os.environ.get("TPU_CC_IDENTITY", "auto").lower()
    if iverdict == "ok":
        _check(checks, "identity", "ok",
               "platform identity token verifies and binds to this node")
    elif iverdict == "unverifiable":
        _check(checks, "identity", "warn",
               f"identity present but {idetail}")
    elif iverdict == "missing" and mode in ("fake", "gce"):
        _check(checks, "identity", "warn",
               f"TPU_CC_IDENTITY={mode} is configured but the "
               "published evidence carries no token — metadata path "
               "broken at publish time? (heals on the next evidence "
               "sync)")
    elif iverdict == "missing":
        # auto/none: absence is the normal posture off-GCE. A GCE
        # metadata OUTAGE also lands here (this host cannot tell the
        # two apart without probing) — the fleet audit's mixed-pool
        # identity_missing finding is the detector for that case.
        _check(checks, "identity", "ok",
               "no identity attached (no platform identity provider "
               "configured/detected)")
    elif iverdict == "expired":
        _check(checks, "identity", "warn",
               "identity token expired — the refresh loop is not "
               "keeping up")
    else:  # mismatch / invalid
        _check(checks, "identity", "fail",
               f"identity {iverdict}: {idetail}")


def _attestation_check(checks: List[dict], doc: dict,
                       node_name: str) -> None:
    """The node diagnoses its OWN attestation posture: a quote that
    fails to verify, commits to a different document, or contradicts
    the measured flip history fails HERE first — before the fleet
    audit's attestation_mismatch finding. Mirrors _identity_check's
    severity vocabulary so the two trust rungs read alike."""
    from tpu_cc_manager.attest import get_attestor, judge_attestation

    averdict, adetail = judge_attestation(doc, node_name)
    # resolved provider, not the env string: 'auto' on a Confidential
    # Space VM HAS a provider, and a quote-less document there is the
    # degradation worth warning about
    configured = get_attestor() is not None
    if averdict == "ok":
        _check(checks, "attestation", "ok",
               "TEE quote verifies and matches the measured flip "
               "history")
    elif averdict == "unverifiable":
        _check(checks, "attestation", "warn",
               f"attestation present but {adetail}")
    elif averdict == "missing" and configured:
        _check(checks, "attestation", "warn",
               "an attestation provider is configured/detected but "
               "the published evidence carries no quote (heals on "
               "the next evidence sync)")
    elif averdict == "expired":
        _check(checks, "attestation", "warn",
               "attestation token expired — the evidence sync is not "
               "keeping up")
    elif averdict == "missing":
        _check(checks, "attestation", "ok",
               "no attestation attached (no TEE provider "
               "configured/detected)")
    else:  # mismatch / invalid
        _check(checks, "attestation", "fail",
               f"attestation {averdict}: {adetail}")


def run_doctor(kube=None, node_name: Optional[str] = None,
               backend=None) -> dict:
    """Execute every check; returns the report dict. Never raises — a
    diagnostic that crashes on the broken state it exists to diagnose
    is useless."""
    from tpu_cc_manager.device.gate import (
        FLIP_LOCK_PERMS, MODE_PERMS, DeviceGate, gating_enabled,
    )
    from tpu_cc_manager.device.holders import check_enabled, find_holders
    from tpu_cc_manager.device.statefile import independent_read

    checks: List[dict] = []
    # ------------------------------------------------------ device local
    try:
        if backend is None:
            from tpu_cc_manager import device as devlayer

            backend = devlayer.get_backend()
        chips, err = backend.find_tpus()
        if err:
            _check(checks, "enumerate", "fail", f"enumeration error: {err}")
            chips = []
        elif not chips:
            _check(checks, "enumerate", "warn", "no TPU devices found")
        else:
            _check(checks, "enumerate", "ok",
                   f"{len(chips)} device(s): "
                   f"{[c.path for c in chips]}")
    except Exception as e:
        _check(checks, "enumerate", "fail", f"backend unavailable: {e}")
        chips = []
        backend = None

    store = getattr(backend, "store", None)
    effective_cc = {}
    for c in chips:
        path = c.path
        try:
            if store is not None:
                pending = [
                    (dom, store.staged(path, dom), store.effective(path, dom))
                    for dom in ("cc", "ici")
                    if store.staged(path, dom) != store.effective(path, dom)
                ]
                if pending:
                    _check(
                        checks, "staged-committed", "fail",
                        f"{path}: staged mode(s) pending over effective "
                        f"(interrupted flip): {pending}",
                    )
                else:
                    _check(checks, "staged-committed", "ok",
                           f"{path}: staged == effective")
                mine = store.effective(path, "cc")
                other = independent_read(store, path, "cc")
                if mine != other:
                    _check(
                        checks, "independent-read", "fail",
                        f"{path}: store reads cc={mine!r} but the "
                        f"independent reader sees {other!r} "
                        "(statefile corruption or implementation skew)",
                    )
                else:
                    _check(checks, "independent-read", "ok",
                           f"{path}: cc={mine!r} agrees across readers")
                effective_cc[path] = other
            elif getattr(c, "is_cc_query_supported", False):
                effective_cc[path] = c.query_cc_mode()
        except Exception as e:
            _check(checks, "staged-committed", "fail", f"{path}: {e}")

    try:
        if gating_enabled() and chips:
            gate = DeviceGate()
            for c in chips:
                perms = gate.current_perms(c.path)
                if perms is None:
                    continue  # no devfs node (fake/jax identities)
                mode = effective_cc.get(c.path)
                if mode is None:
                    # the effective mode could not be established (the
                    # statefile check above already failed for this
                    # device): judging drift against an assumed mode
                    # would misdirect the operator from the real problem
                    _check(
                        checks, "gate-perms", "warn",
                        f"{c.path}: effective mode unknown; gate check "
                        "skipped (see staged-committed)",
                    )
                    continue
                want = MODE_PERMS.get(mode, MODE_PERMS["on"])
                if perms == FLIP_LOCK_PERMS:
                    _check(
                        checks, "gate-perms", "warn",
                        f"{c.path}: flip-locked (0o000) — mid-flip, or a "
                        "failed flip held fail-secure; a successful "
                        "reconcile reopens it",
                    )
                elif perms != want:
                    _check(
                        checks, "gate-perms", "fail",
                        f"{c.path}: perms {oct(perms)} do not encode "
                        f"cc={mode!r} (want {oct(want)}) — drift; the "
                        "agent's idle tick heals this when gating is on",
                    )
                else:
                    _check(checks, "gate-perms", "ok",
                           f"{c.path}: {oct(perms)} encodes cc={mode!r}")
    except Exception as e:
        _check(checks, "gate-perms", "fail", f"gate check error: {e}")

    try:
        if check_enabled() and chips:
            for c in chips:
                holders = find_holders(c.path)
                if holders:
                    _check(
                        checks, "holders", "warn",
                        f"{c.path}: held by "
                        f"{[(h.pid, h.comm) for h in holders]} — fine "
                        "between flips; a flip will wait/restart them",
                    )
                else:
                    _check(checks, "holders", "ok", f"{c.path}: free")
    except Exception as e:
        _check(checks, "holders", "warn", f"holder scan error: {e}")

    # ---------------------------------------------------------- cluster
    node = None
    if kube is not None and node_name:
        try:
            node = kube.get_node(node_name)
        except Exception as e:
            _check(checks, "cluster", "warn",
                   f"cannot read node {node_name!r}: {e} — cluster "
                   "checks skipped")
    else:
        _check(checks, "cluster", "warn",
               "no API server / NODE_NAME: cluster checks skipped")

    if node is not None:
        labels = node["metadata"].get("labels") or {}
        desired = labels.get(L.CC_MODE_LABEL)
        state = labels.get(L.CC_MODE_STATE_LABEL)
        device_mode = _node_mode_from_devices(chips, store)
        if state is not None and state != "failed" and device_mode \
                is not None and state != device_mode:
            _check(
                checks, "state-label", "fail",
                f"cc.mode.state={state!r} but devices read "
                f"{device_mode!r} — the label lies; the evidence audit "
                "flags this fleet-wide, doctor catches it locally",
            )
        else:
            _check(checks, "state-label", "ok",
                   f"cc.mode.state={state!r}, devices={device_mode!r}")
        if desired is not None and desired != state:
            _check(
                checks, "desired-converged", "warn",
                f"desired {desired!r} != observed {state!r} — the agent "
                "may still be reconciling (or has failed; see "
                "state-label / Events)",
            )
        else:
            _check(checks, "desired-converged", "ok",
                   f"desired == observed ({state!r})")

        raw = (node["metadata"].get("annotations") or {}).get(
            L.EVIDENCE_ANNOTATION
        )
        if not raw:
            _check(checks, "evidence", "warn",
                   "no evidence annotation published")
        else:
            try:
                from tpu_cc_manager.evidence import (
                    evidence_keys, evidence_mode, signed_with_primary,
                    verify_evidence,
                )

                doc = json.loads(raw)
                # one key-file read, one snapshot: the verify below and
                # the stale-key check further down must judge against
                # the SAME key set, or a Secret rotating between two
                # reads yields a self-contradictory verdict
                ekeys = evidence_keys()
                ok, reason = verify_evidence(doc, key=ekeys,
                                             backend=backend)
                attested = evidence_mode(doc) if ok else None
                if not ok and reason == "no_key":
                    # signed evidence, no local key: a blind spot for
                    # THIS invocation, not a node problem (same
                    # tolerance the rollout judge applies)
                    _check(checks, "evidence", "warn",
                           "evidence is HMAC-signed but no "
                           "TPU_CC_EVIDENCE_KEY is available here; "
                           "cannot judge it")
                elif not ok:
                    _check(checks, "evidence", "fail",
                           f"evidence does not verify: {reason}")
                elif doc.get("node") != node_name:
                    _check(checks, "evidence", "fail",
                           f"evidence belongs to node "
                           f"{doc.get('node')!r} (replayed?)")
                elif (attested is not None and state not in
                        (None, "failed") and attested != state):
                    _check(checks, "evidence", "fail",
                           f"evidence attests {attested!r} but label "
                           f"claims {state!r}")
                else:
                    if (len(ekeys) > 1
                            and not signed_with_primary(doc, key=ekeys)):
                        # mid-rotation: valid under the tail key only —
                        # the sync healer will re-sign; warn (not fail)
                        # so a rotating fleet doesn't read as broken
                        _check(checks, "evidence", "warn",
                               "evidence verifies only under a "
                               "rotation-tail key; re-sign pending "
                               "(evidence sync will heal this)")
                    else:
                        _check(checks, "evidence", "ok",
                               f"verifies ({reason}), "
                               f"attests {attested!r}")
                _identity_check(checks, doc, node_name)
                _attestation_check(checks, doc, node_name)
            except Exception as e:
                _check(checks, "evidence", "fail",
                       f"evidence unreadable: {e}")

        taints = (node.get("spec") or {}).get("taints") or []
        flip = [t for t in taints if t.get("key") == L.FLIP_TAINT_KEY]
        if flip:
            _check(
                checks, "flip-taint", "warn",
                "flip taint present — a flip is in progress, or a "
                "crashed agent left it; the agent clears it on its next "
                "reconcile",
            )
        else:
            _check(checks, "flip-taint", "ok", "no flip taint")

    return {
        "node": node_name,
        "ok": all(c["severity"] != "fail" for c in checks),
        "checks": checks,
    }


def publish_report(kube, node_name: str, report: dict) -> bool:
    """Push a compact doctor verdict as a node annotation for the fleet
    controller to aggregate. Best-effort."""
    import time

    summary = {
        "ok": report["ok"],
        "fail": sorted({c["name"] for c in report["checks"]
                        if c["severity"] == "fail"}),
        "warn": sorted({c["name"] for c in report["checks"]
                        if c["severity"] == "warn"}),
        "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    try:
        # one merge patch for both: the annotation carries the detail,
        # the label is the selectable mirror (kubectl get nodes
        # -l cc.doctor.ok=false)
        kube.patch_node(node_name, {"metadata": {
            "annotations": {
                L.DOCTOR_ANNOTATION: json.dumps(
                    summary, sort_keys=True, separators=(",", ":")
                ),
            },
            "labels": {
                L.DOCTOR_OK_LABEL: "true" if summary["ok"] else "false",
            },
        }})
        return True
    except Exception:
        log.warning("doctor verdict publication failed", exc_info=True)
        return False


def main_from_args(cfg, args) -> int:
    """CLI glue (called from __main__): build the kube client when
    possible, run, print, exit 0/1."""
    kube = None
    if not args.offline and cfg.node_name:
        try:
            from tpu_cc_manager.k8s.client import HttpKubeClient, KubeConfig

            kube = HttpKubeClient(KubeConfig.load(cfg.kubeconfig))
        except Exception as e:
            log.warning("no API access (%s); running device-local only", e)
    report = run_doctor(kube=kube, node_name=cfg.node_name or None)
    if args.publish and kube is not None and cfg.node_name:
        publish_report(kube, cfg.node_name, report)
    elif args.publish:
        log.warning("--publish needs API access and NODE_NAME; skipped")
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0 if report["ok"] else 1
