"""Platform attestation — the TEE rung of the evidence chain.

Round-4 closed cross-node forgery: evidence is HMAC-signed with the
pool key AND carries the node's platform-identity token, so a stolen
pool key cannot speak for another node. The residual
(docs/security.md) was node-root forgery: root ON the node can
rewrite the durable statefile, read the node's own mounted pool key,
obtain the node's own identity token (it runs on the instance), and
publish perfectly-signed lies. Nothing in that chain is rooted below
the host OS.

This module adds the missing rung: a quote over the evidence document
from a root the host OS cannot counterfeit —

- ``FakeTpm`` (tests / smoke / TPU_CC_ATTESTATION=fake): a software
  TPM double with ONE extend-only PCR and a measured flip log. The
  mode engine extends the PCR on every REAL mode transition
  (engine.py calls :func:`note_mode_applied`); a quote signs
  (nonce, pcr, log) with the attestation key. The security property
  modeled: root can rewrite the statefile and re-sign evidence, and
  can even request a fresh quote over the forged document — but the
  forged CLAIM ("cc is on") contradicts the measured flip history
  ("last real transition was to off"), and extend-only history cannot
  be rewritten. On a real TPM the extend is rooted in hardware; the
  double trusts its state directory instead (the drill rewrites the
  statefile, not the TPM state — exactly the attack surface split a
  real vTPM gives you).
- ``ConfidentialSpaceAttestor`` (TPU_CC_ATTESTATION=confidential-space,
  or ``auto`` when the launcher socket exists): fetches a Google
  Confidential Space attestation token from the in-VM launcher's unix
  socket with the evidence digest as the EAT nonce. The token is an
  RS256 JWT verified offline against a provisioned JWKS
  (TPU_CC_ATTESTATION_JWKS_FILE — same no-public-internet posture as
  identity's JWKS). Confidential Space attests the VM/container
  measurement at the platform level, so there is no per-flip PCR to
  extend; nonce binding is the whole check.

The quote is attached INSIDE the evidence document before the pool-key
digest is computed, and its nonce commits to everything else in the
document (the canonical body minus ``digest``/``attestation``): a
verifier that accepts the quote knows it was minted for exactly this
document.

Verdicts (``judge_attestation``): ``ok | missing | invalid | mismatch
| unverifiable`` — deliberately the same shape as identity's, but a
SEPARATE axis: the fleet audit reports ``attestation_missing`` /
``attestation_mismatch`` buckets so an operator can tell "no TEE
configured" from "the TEE contradicts the evidence".

Env knobs (documented in config.py):

- ``TPU_CC_ATTESTATION``: ``auto`` (default: Confidential Space socket
  if present, else none — a bare /dev/tpm0 is logged but unusable
  without a userspace TPM stack), ``fake``, ``confidential-space``,
  ``none``.
- ``TPU_CC_TPM_STATE_DIR``: the FakeTpm's "hardware" state (PCR + log);
  defaults to ``$TPU_CC_STATE_DIR/tpm``.
- ``TPU_CC_TPM_KEY[_FILE]``: the FakeTpm quote-signing key (the test
  double's stand-in for an AIK; shared with verifiers like the pool
  evidence key).
- ``TPU_CC_ATTESTATION_JWKS_FILE``: JWKS for offline verification of
  Confidential Space tokens.
- ``TPU_CC_REQUIRE_ATTESTATION``: verifiers flag attestation-less
  evidence even on an all-missing pool (otherwise missing is only
  flagged on MIXED pools, mirroring identity).

Reference anchor: the hardware-enforced mode this approximates is the
reference's register-level CC flip (/root/reference/main.py:282-296),
where silicon — not a host-side file — holds the mode.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import json
import logging
import os
import threading
import time
from typing import List, Optional, Tuple

log = logging.getLogger("tpu-cc-manager.attest")

ATTESTATION_VERSION = 1

#: the PCR's reset value (SHA-256 bank convention: all zeros)
PCR_INITIAL = "0" * 64

#: Confidential Space launcher socket (the in-VM token endpoint)
CS_SOCKET_DEFAULT = "/run/container_launcher/teeserver.sock"


# ------------------------------------------------------------ key/env
def tpm_keys() -> Tuple[bytes, ...]:
    """All accepted attestation keys, SIGNING (primary) key first.

    The PRIMARY — TPU_CC_TPM_KEY (the WHOLE inline value) or the whole
    stripped content of TPU_CC_TPM_KEY_FILE — signs every new quote;
    its legacy whole-value semantics are untouched, so a raw-random
    key containing a newline neither changes meaning on upgrade nor
    silently truncates. TPU_CC_TPM_OLD_KEYS (inline) or
    TPU_CC_TPM_OLD_KEYS_FILE lists RETIRED keys one per line, accepted
    for verification only — the rotation-tail posture mirrored from
    the evidence pool key (evidence.evidence_keys). Without the tail,
    rotating the attestation key mid-scan would make every verifier
    read the fleet's still-old quotes as ``mismatch`` — an
    attack-shaped verdict for a routine operation. Retired keys must
    therefore be newline-free (base64/hex keys are; raw-binary retired
    keys should be re-cut). A missing key/file is silent
    (optional-Secret posture); retired keys alone keep this verifier
    keyless, exactly like evidence's rule."""
    primary = tpm_key()
    if primary is None:
        return ()
    keys: Tuple[bytes, ...] = (primary,)
    raw = os.environ.get("TPU_CC_TPM_OLD_KEYS", "").encode()
    if not raw:
        old_path = os.environ.get("TPU_CC_TPM_OLD_KEYS_FILE", "")
        if old_path:
            try:
                with open(old_path, "rb") as f:
                    raw = f.read()
            except OSError:
                raw = b""
    for line in raw.splitlines():
        line = line.strip()
        if line and line not in keys:
            keys = keys + (line,)
    return keys


def tpm_key() -> Optional[bytes]:
    """The PRIMARY (signing) FakeTpm quote key, or None. Verifiers
    should resolve :func:`tpm_keys` instead so rotation-tail keys stay
    accepted."""
    inline = os.environ.get("TPU_CC_TPM_KEY", "")
    if inline:
        return inline.encode()
    path = os.environ.get("TPU_CC_TPM_KEY_FILE", "")
    if path:
        try:
            with open(path, "rb") as f:
                return f.read().strip() or None
        except OSError:
            return None
    return None


def require_attestation() -> bool:
    return os.environ.get(
        "TPU_CC_REQUIRE_ATTESTATION", ""
    ).lower() in ("1", "true", "yes")


def _canonical(obj) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


# ----------------------------------------------------------- PCR math
def extend_pcr(pcr_hex: str, event: str) -> str:
    """One TPM-style extend: PCR' = H(PCR || H(event))."""
    return hashlib.sha256(
        bytes.fromhex(pcr_hex) + hashlib.sha256(event.encode()).digest()
    ).hexdigest()


def replay_log(events: List[str]) -> str:
    """The PCR value a log of events folds to — the verifier-side half
    of extend-only history."""
    pcr = PCR_INITIAL
    for e in events:
        pcr = extend_pcr(pcr, str(e))
    return pcr


def measured_mode(events: List[str]) -> Optional[str]:
    """The last REAL mode transition the measured log records (events
    are ``mode:<value>``); None when no transition was ever measured."""
    for e in reversed(list(events)):
        if isinstance(e, str) and e.startswith("mode:"):
            return e[len("mode:"):]
    return None


# ------------------------------------------------------------ FakeTpm
class FakeTpm:
    """Software TPM double: one extend-only PCR persisted in a state
    directory, quotes HMAC-signed with the attestation key. The state
    dir plays the role of hardware — the node-root drill rewrites the
    STATEFILE, not this directory, because on real silicon the PCR is
    out of the filesystem entirely."""

    provider = "fake-tpm"

    def __init__(self, state_dir: Optional[str] = None,
                 key: Optional[bytes] = None):
        if state_dir is None:
            state_dir = os.environ.get("TPU_CC_TPM_STATE_DIR") or \
                os.path.join(
                    os.environ.get("TPU_CC_STATE_DIR", "/var/lib/tpu-cc"),
                    "tpm",
                )
        self.state_dir = state_dir
        self._key = key
        self._lock = threading.Lock()

    def _key_bytes(self) -> Optional[bytes]:
        return self._key if self._key is not None else tpm_key()

    def set_key(self, key: Optional[bytes]) -> None:
        """Swap the quote-signing key (the key-rotation drill: the node
        re-quotes under the new key on its next evidence build; the
        verifier keeps the old key in its rotation tail until the fleet
        has re-quoted). The measured log is untouched — rotation
        changes who vouches, not what happened."""
        with self._lock:
            self._key = key

    def _log_path(self) -> str:
        return os.path.join(self.state_dir, "log")

    def _read_state(self) -> Tuple[str, List[str]]:
        """(pcr, events). The append-only log is the ONLY persisted
        state — the PCR is derived by replay, so there is no two-file
        update to interrupt: a crash mid-extend leaves at worst a
        complete log line or none, never a log that disagrees with a
        separately-stored PCR (which would read as 'mismatch'
        forever)."""
        events: List[str] = []
        try:
            with open(self._log_path()) as f:
                events = [ln.rstrip("\n") for ln in f if ln.strip()]
        except OSError:
            pass
        return replay_log(events), events

    def extend(self, event: str) -> str:
        """Fold ``event`` into the measured log; returns the new PCR.
        Called by the mode engine on every REAL transition (never on
        the idempotent fast path — the log is flip history, not
        reconcile history). One O_APPEND write: atomic enough across
        the in-process agent and the bash engine's separate --extend
        process; the lock covers same-process threads."""
        with self._lock:
            os.makedirs(self.state_dir, exist_ok=True)
            with open(self._log_path(), "a") as f:
                f.write(event + "\n")
            pcr, _ = self._read_state()
            return pcr

    def quote(self, nonce_hex: str) -> dict:
        """Sign (nonce, pcr, log) — the log rides along so verifiers
        can replay it (TPM quote + event log, in one envelope)."""
        with self._lock:
            pcr, events = self._read_state()
        body = {
            "version": ATTESTATION_VERSION,
            "provider": self.provider,
            "nonce": nonce_hex,
            "pcr": pcr,
            "log": events,
        }
        key = self._key_bytes()
        if key:
            body["sig"] = hmac_mod.new(
                key, _canonical(body), hashlib.sha256
            ).hexdigest()
        return body


# ----------------------------------------- Confidential Space (real)
class ConfidentialSpaceAttestor:
    """Fetch a Confidential Space attestation token from the in-VM
    launcher socket, with the evidence digest as the EAT nonce. Only
    meaningful inside a Confidential Space VM; ``probe`` gates
    ``auto``."""

    provider = "confidential-space"

    def __init__(self, socket_path: Optional[str] = None,
                 timeout_s: float = 2.0):
        self.socket_path = socket_path or os.environ.get(
            "TPU_CC_CS_SOCKET", CS_SOCKET_DEFAULT
        )
        self.timeout_s = timeout_s

    def probe(self) -> bool:
        return os.path.exists(self.socket_path)

    def quote(self, nonce_hex: str) -> dict:
        import http.client
        import socket as socket_mod

        class _UnixConn(http.client.HTTPConnection):
            def __init__(conn_self, path, timeout):
                super().__init__("localhost", timeout=timeout)
                conn_self._path = path

            def connect(conn_self):
                s = socket_mod.socket(
                    socket_mod.AF_UNIX, socket_mod.SOCK_STREAM
                )
                s.settimeout(conn_self.timeout)
                s.connect(conn_self._path)
                conn_self.sock = s

        conn = _UnixConn(self.socket_path, self.timeout_s)
        try:
            body = json.dumps({
                "audience": "tpu-cc-manager",
                "token_type": "OIDC",
                "nonces": [nonce_hex],
            })
            conn.request("POST", "/v1/token", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            token = resp.read().decode()
            if resp.status != 200 or not token:
                raise RuntimeError(
                    f"launcher token endpoint: http {resp.status}"
                )
        finally:
            conn.close()
        return {
            "version": ATTESTATION_VERSION,
            "provider": self.provider,
            "nonce": nonce_hex,
            "token": token,
        }


# -------------------------------------------------------- resolution
_cache: dict = {}
_warned_tpm_device = False


def get_attestor(refresh: bool = False):
    """Resolve the node's attestor from TPU_CC_ATTESTATION. ``auto``
    takes the Confidential Space socket when present; a bare /dev/tpm0
    is logged once and SKIPPED (no userspace TPM stack is vendored —
    set an explicit mode to opt in); otherwise none."""
    global _warned_tpm_device
    mode = os.environ.get("TPU_CC_ATTESTATION", "auto").lower()
    if mode in ("none", "off", "false", ""):
        return None
    if refresh:
        _cache.pop(mode, None)
    if mode in _cache:
        return _cache[mode]
    if mode == "fake":
        _cache[mode] = FakeTpm()
    elif mode in ("confidential-space", "cs"):
        _cache[mode] = ConfidentialSpaceAttestor()
    elif mode == "auto":
        cs = ConfidentialSpaceAttestor()
        if cs.probe():
            _cache[mode] = cs
        else:
            if os.path.exists("/dev/tpm0") and not _warned_tpm_device:
                _warned_tpm_device = True
                log.info(
                    "/dev/tpm0 present but no userspace TPM stack is "
                    "vendored; set TPU_CC_ATTESTATION explicitly to "
                    "opt in to an attestation provider"
                )
            _cache[mode] = None
    else:
        log.warning("unknown TPU_CC_ATTESTATION=%r; attestation off",
                    mode)
        _cache[mode] = None
    return _cache[mode]


def note_mode_applied(mode: str) -> None:
    """Measured flip history: the mode engine calls this after every
    REAL (non-idempotent) successful transition. Best-effort — a
    broken TPM state dir must not fail a flip — and a no-op for
    providers without per-flip measurement (Confidential Space)."""
    att = get_attestor()
    extend = getattr(att, "extend", None)
    if extend is None:
        return
    try:
        extend(f"mode:{mode}")
    except Exception:
        log.warning("attestation extend failed; measured flip history "
                    "will lag", exc_info=True)


# ------------------------------------------------------- verification
def attestation_nonce(doc: dict) -> str:
    """What a quote for this document must commit to: SHA-256 of the
    canonical body minus ``digest`` (computed after the quote) and
    ``attestation`` (the quote itself)."""
    body = {k: v for k, v in doc.items()
            if k not in ("digest", "attestation")}
    return hashlib.sha256(_canonical(body)).hexdigest()


def verify_quote(att: dict, expected_nonce: str, *,
                 key: Optional[bytes] = None
                 ) -> Tuple[str, str]:
    """Judge a fake-tpm quote against the nonce it should commit to.
    Returns (verdict, detail): ok | invalid | mismatch | unverifiable.
    """
    if not isinstance(att, dict):
        return "invalid", "attestation field malformed"
    if att.get("provider") != FakeTpm.provider:
        return "invalid", f"unknown provider {att.get('provider')!r}"
    nonce = att.get("nonce")
    pcr = att.get("pcr")
    events = att.get("log")
    if not isinstance(nonce, str) or not isinstance(pcr, str) \
            or not isinstance(events, list):
        return "invalid", "quote shape malformed"
    if nonce != expected_nonce:
        return "mismatch", (
            "quote nonce does not commit to this document (quote "
            "replayed from another document?)"
        )
    if replay_log([str(e) for e in events]) != pcr:
        return "mismatch", "event log does not replay to the quoted PCR"
    # key=None resolves the env posture INCLUDING the rotation tail
    # (tpm_keys): during a key rotation the fleet's still-old quotes
    # must verify under a retired key instead of reading as forgery.
    # A tuple/list is an EXPLICIT posture (per-region trust roots,
    # federation): its keys verbatim, and an empty one means an
    # explicitly keyless verifier — 'unverifiable', never env fallback
    # (a revoked region must not inherit the process-global root).
    if key is None:
        keys: Tuple[bytes, ...] = tpm_keys()
    elif isinstance(key, (tuple, list)):
        keys = tuple(key)
    else:
        keys = (key,)
    if not keys:
        return "unverifiable", (
            "no attestation key provisioned (TPU_CC_TPM_KEY[_FILE]) — "
            "quote cannot be authenticated"
        )
    body = {k: v for k, v in att.items() if k != "sig"}
    sig = str(att.get("sig") or "")
    payload = _canonical(body)
    for k in keys:
        want = hmac_mod.new(k, payload, hashlib.sha256).hexdigest()
        if hmac_mod.compare_digest(want, sig):
            return "ok", "quote verifies"
    return "mismatch", "quote signature does not verify"


def _judge_cs_token(att: dict, expected_nonce: str) -> Tuple[str, str]:
    """Offline verification of a Confidential Space token against the
    provisioned JWKS, nonce included."""
    token = att.get("token")
    if not isinstance(token, str) or token.count(".") != 2:
        return "invalid", "attestation token malformed"
    jwks_path = os.environ.get("TPU_CC_ATTESTATION_JWKS_FILE", "")
    if not jwks_path:
        return "unverifiable", (
            "no TPU_CC_ATTESTATION_JWKS_FILE provisioned — token "
            "cannot be verified offline"
        )
    from tpu_cc_manager.identity import (
        _b64url_decode, _rsa_pkcs1_sha256_verify, load_jwks,
    )

    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
        header = json.loads(_b64url_decode(header_b64))
        payload = json.loads(_b64url_decode(payload_b64))
    except Exception as e:
        return "invalid", f"attestation token undecodable: {e}"
    try:
        keys = load_jwks(jwks_path)
    except Exception as e:
        # an operator config error (truncated ConfigMap, unreadable
        # mount) must read as unverifiable, never as a fleet-wide
        # forgery alarm
        return "unverifiable", f"JWKS unreadable: {e}"
    kid = header.get("kid")
    if kid not in keys:
        return "unverifiable", f"token kid {kid!r} not in JWKS"
    n, e = keys[kid]
    signing_input = f"{header_b64}.{payload_b64}".encode()
    try:
        sig = _b64url_decode(sig_b64)
        if not _rsa_pkcs1_sha256_verify(n, e, signing_input, sig):
            return "mismatch", "token signature does not verify"
    except Exception as e:
        return "invalid", f"token signature undecodable: {e}"
    exp = payload.get("exp")
    if isinstance(exp, (int, float)) and exp < time.time():
        # staleness, not forgery: the platform DID attest, the token
        # simply aged out on an idle node — classed like identity's
        # expired (missing-shaped), never as the forgery alarm
        return "expired", "attestation token expired"
    nonces = payload.get("eat_nonce")
    if isinstance(nonces, str):
        nonces = [nonces]
    if not isinstance(nonces, list) or expected_nonce not in nonces:
        return "mismatch", (
            "token eat_nonce does not commit to this document"
        )
    return "ok", "attestation token verifies"


def judge_attestation(doc: dict, node_name: Optional[str] = None, *,
                      key: Optional[bytes] = None
                      ) -> Tuple[str, str]:
    """Judge the ``attestation`` field of an evidence document. Returns
    (verdict, detail) with verdicts ``ok | missing | expired | invalid
    | mismatch | unverifiable`` — a separate axis from identity, so
    the fleet audit can distinguish "no TEE" from "TEE contradicts the
    evidence". ``expired`` (a Confidential Space token that aged out)
    is staleness, classed with missing by every verifier. The
    node-root drill lands in ``mismatch``: a forged claim's measured
    flip history disagrees with the mode the document attests."""
    if not isinstance(doc, dict):
        return "invalid", "document malformed"
    att = doc.get("attestation")
    if att is None:
        return "missing", "no attestation attached"
    expected = attestation_nonce(doc)
    if isinstance(att, dict) and att.get("provider") == \
            ConfidentialSpaceAttestor.provider:
        return _judge_cs_token(att, expected)
    verdict, detail = verify_quote(att, expected, key=key)
    if verdict not in ("ok", "unverifiable"):
        return verdict, detail
    # the root-forgery check: the document's device-truth claim must
    # agree with the MEASURED flip history. This comparison needs NO
    # key — the nonce commitment and PCR replay already passed — so it
    # runs even for 'unverifiable' quotes (keyless verifier host):
    # same principle as the evidence path's keyless-checkable claims.
    # It only catches forgers too lazy to fabricate a whole quote
    # there (no signature binds the log), but a contradiction is a
    # contradiction. An empty log is lenient (attestation enabled
    # mid-life, no transition measured yet) — strictness there would
    # flag every fresh enablement.
    from tpu_cc_manager.evidence import evidence_mode

    measured = measured_mode(att.get("log") or [])
    claimed = evidence_mode(doc)
    if measured is not None and claimed is not None \
            and measured != claimed:
        qualifier = (
            " (quote signature unverifiable here — but the claim "
            "contradiction needs no key to read)"
            if verdict == "unverifiable" else ""
        )
        return "mismatch", (
            f"document attests mode {claimed!r} but the measured flip "
            f"history's last real transition was to {measured!r} — "
            "state was changed outside the measured engine path "
            f"(node-root statefile rewrite?){qualifier}"
        )
    if verdict == "unverifiable":
        return verdict, detail
    return "ok", "quote verifies and matches measured history"


def quote_refresh_deadline(doc: dict) -> Optional[float]:
    """Wall-clock time at which the evidence should be republished
    because its attestation token nears expiry — the attestation twin
    of the agent's identity-refresh deadline, and the freshness input
    ``evidence_in_sync`` uses for Confidential Space quotes (fake-tpm
    quotes carry no expiry: their freshness is the key posture). None
    when there is nothing to age out."""
    att = doc.get("attestation") if isinstance(doc, dict) else None
    if not isinstance(att, dict) or att.get("provider") != \
            ConfidentialSpaceAttestor.provider:
        return None
    token = att.get("token")
    if not isinstance(token, str) or token.count(".") != 2:
        return None
    from tpu_cc_manager.identity import REPUBLISH_MARGIN, token_claims

    try:
        _, claims = token_claims(token)
        exp = claims.get("exp")
        if not isinstance(exp, (int, float)):
            return None
        iat = claims.get("iat")
        if isinstance(iat, (int, float)):
            margin = REPUBLISH_MARGIN * max(float(exp) - float(iat), 0.0)
        else:
            margin = 300.0
        return float(exp) - margin
    except Exception:  # ccaudit: allow-swallow(undecodable quote has no expiry to extract; caller treats None as never)
        return None


# --------------------------------------------------------------- CLI
def main(argv=None) -> int:
    """``python -m tpu_cc_manager.attest`` — the bash engine's hook
    into measured history (--extend after a real flip) plus operator
    introspection (--status)."""
    import argparse

    p = argparse.ArgumentParser(prog="tpu-cc-attest")
    p.add_argument("--extend", metavar="MODE",
                   help="record a real mode transition in the "
                        "measured log")
    p.add_argument("--status", action="store_true",
                   help="print the resolved provider and PCR state")
    args = p.parse_args(argv)
    if args.extend:
        note_mode_applied(args.extend)
        return 0
    if args.status:
        att = get_attestor()
        out = {"provider": getattr(att, "provider", None)}
        if isinstance(att, FakeTpm):
            pcr, events = att._read_state()
            out.update(pcr=pcr, log=events,
                       measured_mode=measured_mode(events))
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    p.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
