"""L4 — configuration: flags-over-env, no config files (SURVEY.md §5.6).

Every flag defaults from an environment variable, exactly as the reference
does across its three implementations (reference cmd/main.go:83-117,
main.py:703-759, scripts/cc-manager.sh:5-6):

========================  =============================  =======================
flag                      env                            default
========================  =============================  =======================
--kubeconfig              KUBECONFIG                     in-cluster, else ~/.kube/config
--default-cc-mode / -m    DEFAULT_CC_MODE                "on"
--node-name               NODE_NAME                      (required)
--debug                   CC_MANAGER_DEBUG               false
(none)                    OPERATOR_NAMESPACE             "tpu-system"
(none)                    EVICT_OPERATOR_COMPONENTS      "true"
(none)                    DRAIN_STRATEGY                 "components" | "node" | "none"
(none)                    CC_READINESS_FILE              /run/tpu/validations/.cc-manager-ctr-ready
(none)                    CC_CAPABLE_DEVICE_IDS          "" (all Google chips capable)
--health-port             HEALTH_PORT                    8089 (0 disables)
(none)                    SLICE_COORDINATION             "false"
(none)                    TPU_CC_SLICE_COMMIT_TIMEOUT_S  600 (quorum wait before abort)
(none)                    REPAIR_INTERVAL_S              30 (0 disables self-repair)
(none)                    CC_TRACE_FILE                  "" (JSONL span sink off)
(none)                    TPU_CC_TRACE_JSONL_MAX_MB      0 (size cap on the JSONL span
                                                        sink; rotates to <path>.1 —
                                                        0/unset = unbounded)
(none)                    TPU_CC_LOG_FORMAT              "text" | "json" (JSON records
                                                        carry the active trace_id/span_id
                                                        so logs and traces join)
(none)                    TPU_CC_FLIGHTREC_DIR           "" (flight-recorder dump dir;
                                                        unset = no dumps written, the
                                                        /debug/flightrec route still
                                                        serves the live snapshot)
(none)                    EMIT_EVENTS                    true (reconcile Events)
(none)                    TPU_CC_DEVICE_GATING           "chmod" | "none" (device-node gating)
(none)                    TPU_CC_HOLDER_CHECK            "proc" | "none" (exclusive-hold scan)
(none)                    TPU_CC_RUNTIME_RESTART_CMD     "" (hook to evict an external holder)
(none)                    TPU_CC_HOLD_WAIT_S             30 (grace period for holders to leave)
(none)                    TPU_CC_EVIDENCE                true (per-flip evidence annotation)
(none)                    TPU_CC_EVIDENCE_KEY[_FILE]     "" (HMAC key; unset = plain sha256)
(none)                    TPU_CC_EVIDENCE_OLD_KEYS_FILE  "" (retired keys, one per line,
                                                        verify-only — key rotation)
(none)                    TPU_CC_KUBE_QPS[/_BURST]       0 = off (client-side API flow
                                                        control; controllers set 50 —
                                                        client-go QPS/Burst parity)
(none)                    TPU_CC_KUBE_AIO                unset (1 = the async I/O core:
                                                        one event loop multiplexing
                                                        pipelined connections behind a
                                                        sync facade — docs/io.md; not
                                                        for exec-plugin auth)
(none)                    TPU_CC_KUBE_INFLIGHT           4 (per-connection pipelined
                                                        in-flight window, async core)
(none)                    TPU_CC_FLEET_MIN_SCAN_GAP_S    5 (coalescing gap between
                                                        watch-triggered fleet scans)
(none)                    TPU_CC_POLICY_MIN_SCAN_GAP_S   2 (coalescing gap after any
                                                        policy-scan wake)
(none)                    TPU_CC_MAX_ROLLOUTS            3 (policy controller rollout
                                                        worker slots: disjoint pools
                                                        roll concurrently; 1 = strict
                                                        serialization)
(none)                    TPU_CC_IDENTITY                auto | gce | fake | none (platform
                                                        identity attached to evidence)
(none)                    TPU_CC_IDENTITY_KEY[_FILE]     "" (HS256 key, fake provider only)
(none)                    TPU_CC_IDENTITY_AUDIENCE       tpu-cc-manager (token audience)
(none)                    TPU_CC_IDENTITY_JWKS_FILE      "" (JWKS for offline RS256
                                                        verification of GCE tokens)
(none)                    TPU_CC_EVIDENCE_SYNC_INTERVAL_S 300 (native agent: idle-tick
                                                        evidence healer; 0 disables)
(none)                    TPU_CC_WEBHOOK_REQUIRE_DOCTOR  false (webhook also pins opted-in
                                                        pods to cc.doctor.ok=true nodes)
(none)                    TPU_CC_METADATA_HOST           metadata.google.internal
(none)                    TPU_CC_REQUIRE_IDENTITY        false (verifiers flag identity-less
                                                        evidence even on uniform pools)
(none)                    TPU_CC_ATTESTATION             auto | fake | confidential-space |
                                                        none (TEE quote over evidence;
                                                        auto = CS launcher socket if
                                                        present)
(none)                    TPU_CC_TPM_STATE_DIR           $TPU_CC_STATE_DIR/tpm (FakeTpm
                                                        PCR + measured flip log)
(none)                    TPU_CC_TPM_KEY[_FILE]          "" (FakeTpm quote key — the test
                                                        double's AIK stand-in)
(none)                    TPU_CC_ATTESTATION_JWKS_FILE   "" (JWKS for offline verification
                                                        of Confidential Space tokens)
(none)                    TPU_CC_REQUIRE_ATTESTATION     false (verifiers flag quote-less
                                                        evidence even on uniform pools)
(none)                    KUBE_API_TLS                   false (native agent + bash engine:
                                                        direct HTTPS, no proxy sidecar)
(none)                    KUBE_CA_FILE                   serviceaccount ca.crt (with TLS)
(none)                    BEARER_TOKEN_FILE              "" (SA token for direct API auth)
--interval (fleet)        FLEET_SCAN_INTERVAL            30 (seconds)
--port (fleet)            FLEET_PORT                     8090
(none)                    TPU_CC_LEADER_ELECT            false (controllers: Lease-based
                                                        leader election; replicas: 2 safe)
(none)                    POD_NAME                       "" (lease holder identity; the
                                                        manifests set it via downward API)
(none)                    OPERATOR_NAMESPACE             tpu-system (also where the
                                                        election Leases live)
(none)                    TPU_CC_SIMLAB_WORKERS          0 = scenario's value (simlab:
                                                        reconcile worker slots shared
                                                        by all replicas)
========================  =============================  =======================
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import List, Optional

from tpu_cc_manager import __version__
from tpu_cc_manager import labels as L

#: Readiness file signalling "initial reconcile done" to the validation
#: framework (reference main.py:64: /run/nvidia/validations/...).
DEFAULT_READINESS_FILE = "/run/tpu/validations/.cc-manager-ctr-ready"


def _env_float(name: str, default: float) -> float:
    """Float env knob: unset, empty, or unparseable reads as the
    default (a typo must degrade to documented behavior, not crash a
    controller at startup)."""
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass
class AgentConfig:
    node_name: str
    default_mode: str = "on"
    kubeconfig: Optional[str] = None
    debug: bool = False
    operator_namespace: str = "tpu-system"
    evict_components: bool = True
    drain_strategy: str = "components"  # components | node | none
    readiness_file: str = DEFAULT_READINESS_FILE
    health_port: int = 8089
    slice_coordination: bool = False
    #: Seconds between self-repair retries of a failed reconcile (device
    #: fault or slice abort). The reference only retries on the *next
    #: label event* (cmd/main.go:164-167) — which for a half-flipped
    #: slice never comes, because the desired label is already correct.
    #: 0 disables.
    repair_interval_s: float = 30.0
    trace_file: Optional[str] = None
    #: Log record format: "text" (historical) or "json" — JSON records
    #: carry the active trace_id/span_id (obs.JsonLogFormatter), so
    #: logs and traces join on one key. TPU_CC_LOG_FORMAT.
    log_format: str = "text"
    #: Directory the flight recorder (tpu_cc_manager.flightrec) dumps
    #: its black-box JSON artifacts into on reconcile failure and
    #: SIGTERM. None = dumps disabled; the /debug/flightrec route
    #: serves the live snapshot either way. TPU_CC_FLIGHTREC_DIR.
    flightrec_dir: Optional[str] = None
    #: Emit core/v1 Events on reconcile outcomes so `kubectl describe
    #: node` shows the mode-flip history (the reference surfaces outcomes
    #: only in labels + pod logs). Best-effort; EMIT_EVENTS=false disables.
    emit_events: bool = True
    #: Publish the per-flip attestation evidence annotation
    #: (tpu_cc_manager.evidence). Best-effort; TPU_CC_EVIDENCE=false
    #: disables.
    emit_evidence: bool = True
    #: Seconds between periodic doctor self-checks published as the
    #: cc.doctor node annotation (tpu_cc_manager.doctor), keeping the
    #: fleet controller's trust-surface aggregation fresh without
    #: operator action. 0 disables. TPU_CC_DOCTOR_INTERVAL_S.
    doctor_interval_s: float = 300.0
    #: Seconds a slice member waits for quorum before aborting the round
    #: (slice_coord). Shared by the agent, the one-shot CLI, and through
    #: it the bash engine's slice delegation. TPU_CC_SLICE_COMMIT_TIMEOUT_S.
    slice_commit_timeout_s: float = 600.0

    def __post_init__(self):
        if self.log_format not in ("text", "json"):
            raise ValueError(
                f"invalid TPU_CC_LOG_FORMAT {self.log_format!r}: "
                "must be text|json"
            )
        if self.drain_strategy not in ("components", "node", "none"):
            raise ValueError(
                f"invalid DRAIN_STRATEGY {self.drain_strategy!r}: "
                "must be components|node|none"
            )
        if self.repair_interval_s < 0:
            raise ValueError(
                f"invalid REPAIR_INTERVAL_S {self.repair_interval_s!r}: "
                "must be >= 0 (0 disables self-repair)"
            )
        if self.doctor_interval_s < 0:
            raise ValueError(
                f"invalid TPU_CC_DOCTOR_INTERVAL_S "
                f"{self.doctor_interval_s!r}: must be >= 0 (0 disables)"
            )
        if self.slice_commit_timeout_s <= 0:
            raise ValueError(
                f"invalid TPU_CC_SLICE_COMMIT_TIMEOUT_S "
                f"{self.slice_commit_timeout_s!r}: must be > 0"
            )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-cc-manager",
        description="TPU confidential-computing mode manager for Kubernetes",
    )
    p.add_argument(
        "--version", action="version",
        # native-agent parity (agent.cpp --version; reference Go agent's
        # urfave/cli -v): the image smoke and operators both probe it
        version=f"%(prog)s {__version__}",
    )
    p.add_argument(
        "--kubeconfig",
        default=os.environ.get("KUBECONFIG"),
        help="path to kubeconfig; omit for in-cluster config",
    )
    p.add_argument(
        "-m",
        "--default-cc-mode",
        default=os.environ.get("DEFAULT_CC_MODE", "on"),
        help="mode applied when the node has no cc.mode label (default: on)",
    )
    p.add_argument(
        "--node-name",
        default=os.environ.get("NODE_NAME"),
        help="this node's name (env NODE_NAME; required)",
    )
    p.add_argument(
        "--health-port",
        type=int,
        default=int(os.environ.get("HEALTH_PORT", "8089")),
        help="port for /healthz + /metrics (0 disables; default 8089)",
    )
    p.add_argument(
        "--debug",
        action="store_true",
        default=_env_bool("CC_MANAGER_DEBUG", False),
        help="enable debug logging",
    )
    # one-shot engine subcommands (parity with the bash engine CLI,
    # reference scripts/cc-manager.sh:472-533)
    sub = p.add_subparsers(dest="command")
    set_p = sub.add_parser("set-cc-mode", help="apply a mode once and exit")
    set_p.add_argument("-m", "--mode", required=True)
    set_p.add_argument(
        "-a", "--all-devices", action="store_true", default=True,
        help="operate on all devices (the only supported scope)",
    )
    sub.add_parser("get-cc-mode", help="print per-device modes and exit")
    probe = sub.add_parser(
        "probe-devices",
        help="print the device inventory as JSON (no NODE_NAME needed). "
             "Default backend: jax — the live TPU runtime, i.e. hardware "
             "truth; pass --backend sysfs to inspect the surface a "
             "sysfs-backend agent actually manages.",
    )
    probe.add_argument(
        "--backend",
        choices=("jax", "sysfs", "fake"),
        default=os.environ.get("TPU_CC_DEVICE_BACKEND", "jax"),
        help="device backend to probe (env TPU_CC_DEVICE_BACKEND; "
             "default jax)",
    )
    roll = sub.add_parser(
        "rollout",
        help="roll a mode change across the pool, bounded by a "
             "disruption window (operator-side; no NODE_NAME needed)",
    )
    roll.add_argument("-m", "--mode", default=None,
                      help="target mode (not needed with --resume)")
    roll.add_argument(
        "--resume", action="store_true",
        help="resume the pool's unfinished rollout from its durable "
             "record (anchor-node annotation) after an operator-side "
             "crash; mode/window/budget come from the record",
    )
    roll.add_argument(
        "--selector",
        default=None,
        help="label selector scoping the pool (default: "
             f"{L.TPU_ACCELERATOR_LABEL}). With --resume, an EXPLICIT "
             "selector narrows the search to that pool only — it will "
             "not wander into another pool's unfinished record",
    )
    roll.add_argument(
        "--max-unavailable", type=int, default=1,
        help="slice groups in flight at once (default 1)",
    )
    roll.add_argument(
        "--failure-budget", type=int, default=0,
        help="failed groups tolerated before aborting (default 0)",
    )
    roll.add_argument(
        "--canary", type=int, default=0,
        help="first N groups roll serially and must each succeed "
             "before the window opens; any canary failure aborts "
             "(default 0 = no canary)",
    )
    roll.add_argument(
        "--group-timeout", type=float, default=600.0,
        help="seconds to wait for one group to converge (default 600)",
    )
    roll.add_argument(
        "--force", action="store_true",
        help="proceed despite failed nodes / half-flipped slices",
    )
    roll.add_argument(
        "--dry-run", action="store_true",
        help="print the group plan without patching anything",
    )
    roll.add_argument(
        "--no-verify-evidence", action="store_true",
        help="trust cc.mode.state labels without cross-checking the "
             "per-node attestation evidence",
    )
    fleet = sub.add_parser(
        "fleet-controller",
        help="run the read-only fleet audit service: periodic JAX fleet "
             "scans served as /metrics + /report (operator-side; no "
             "NODE_NAME needed)",
    )
    fleet.add_argument(
        "--selector",
        default=L.TPU_ACCELERATOR_LABEL,
        help="label selector scoping the fleet",
    )
    fleet.add_argument(
        "--interval", type=float,
        default=float(os.environ.get("FLEET_SCAN_INTERVAL", "30")),
        help="seconds between fleet scans (default 30)",
    )
    fleet.add_argument(
        "--port", type=int,
        default=int(os.environ.get("FLEET_PORT", "8090")),
        help="HTTP port for /metrics, /report, /healthz (default 8090)",
    )
    fleet.add_argument(
        "--once", action="store_true",
        help="run one fleet scan, print the report, and exit non-zero "
             "if the audit found problems (failed nodes, evidence "
             "issues, failing doctor verdicts, half-flipped slices) — "
             "cron/CI usage",
    )
    pol = sub.add_parser(
        "policy-controller",
        help="run the declarative TPUCCPolicy controller: continuously "
             "reconcile the fleet to the modes the cluster's TPUCCPolicy "
             "objects declare, driving bounded rollouts and publishing "
             "status (operator-side; no NODE_NAME needed)",
    )
    pol.add_argument(
        "--interval", type=float,
        default=float(os.environ.get("POLICY_SCAN_INTERVAL", "30")),
        help="seconds between policy scans (default 30)",
    )
    pol.add_argument(
        "--port", type=int,
        default=int(os.environ.get("POLICY_PORT", "8091")),
        help="HTTP port for /metrics, /report, /healthz (default 8091)",
    )
    pol.add_argument(
        "--no-verify-evidence", action="store_true",
        help="trust cc.mode.state labels without cross-checking the "
             "per-node attestation evidence",
    )
    pol.add_argument(
        "--once", action="store_true",
        help="run one reconcile pass, print the report, and exit "
             "non-zero if any policy is Invalid/Conflicted/Degraded "
             "(cron/CI usage)",
    )
    wh = sub.add_parser(
        "webhook",
        help="run the admission webhook: steer pods labeled "
             f"{L.REQUIRES_CC_LABEL} onto nodes whose observed mode "
             "matches, and reject contradictory specs (operator-side; "
             "no NODE_NAME needed)",
    )
    wh.add_argument(
        "--port", type=int,
        default=int(os.environ.get("WEBHOOK_PORT", "8443")),
        help="HTTPS port for /mutate, /validate, /healthz (default 8443)",
    )
    wh.add_argument(
        "--cert", default=os.environ.get("WEBHOOK_CERT"),
        help="TLS server certificate (env WEBHOOK_CERT; required)",
    )
    wh.add_argument(
        "--key", default=os.environ.get("WEBHOOK_KEY"),
        help="TLS server key (env WEBHOOK_KEY; defaults to --cert)",
    )
    sim = sub.add_parser(
        "simlab",
        help="fleet-scale scenario lab: run hundreds of live reconciling "
             "agent replicas against the in-process wire-level API "
             "server, execute a declarative scenario (mode storms, "
             "policy rollouts, scripted faults), and emit a JSON "
             "artifact (operator/CI-side; no NODE_NAME needed) — see "
             "docs/simlab.md",
    )
    simsub = sim.add_subparsers(dest="simlab_command")
    sim_run = simsub.add_parser(
        "run", help="execute one scenario file and print the artifact"
    )
    sim_run.add_argument("scenario", help="path to a scenario JSON file")
    sim_run.add_argument(
        "--out", default=None,
        help="also write the artifact JSON to this path",
    )
    sim_run.add_argument(
        "--nodes", type=int, default=0,
        help="override the scenario's node count (0 = as written)",
    )
    sim_run.add_argument(
        "--workers", type=int, default=0,
        help="override the scenario's worker-slot count (0 = as "
             "written; env TPU_CC_SIMLAB_WORKERS also overrides)",
    )
    sim_val = simsub.add_parser(
        "validate", help="validate scenario files against the schema"
    )
    sim_val.add_argument("scenarios", nargs="+",
                         help="scenario JSON files to validate")
    sim_pg = simsub.add_parser(
        "propgen",
        help="property-based lifecycle scenario generation: run seeded "
             "random fault/lifecycle interleavings through the live "
             "harness and the convergence-and-invariants oracle; "
             "violations shrink and persist as replayable "
             "scenarios/gen-*.json (docs/simlab.md)",
    )
    sim_pg.add_argument(
        "--seeds", default="1,2,3,4",
        help="comma-separated episode seeds (default 1,2,3,4)",
    )
    sim_pg.add_argument(
        "--families", default="",
        help="restrict episodes to these fault families (comma-"
             "separated: upgrade,attestation,policy,evacuation,shards; "
             "default: seeded choice)",
    )
    sim_pg.add_argument(
        "--no-shrink", action="store_true",
        help="persist finds without the shrink pass",
    )
    sim_pg.add_argument(
        "--max-shrink-runs", type=int, default=8,
        help="reproduction-run budget per shrink (default 8)",
    )
    sim_pg.add_argument(
        "--scenario-dir", default="scenarios",
        help="where replayable gen-*.json finds land (default "
             "scenarios/)",
    )
    sim_pg.add_argument(
        "--report-dir", default="propgen-finds",
        help="where find reports (violations + stitched timeline) "
             "land (default propgen-finds/)",
    )
    doc = sub.add_parser(
        "doctor",
        help="cross-check every node-local trust surface (statefile, "
             "device gate, holders, labels, evidence) and print a JSON "
             "report; exits non-zero iff a check fails",
    )
    doc.add_argument(
        "--offline", action="store_true",
        help="skip the cluster checks (no API server access attempted)",
    )
    doc.add_argument(
        "--publish", action="store_true",
        help="also push the compact verdict as the cc.doctor node "
             "annotation for the fleet controller to aggregate",
    )
    return p


def parse_config(argv: Optional[List[str]] = None):
    """-> (AgentConfig, parsed_args). Validates NODE_NAME presence like the
    reference (cmd/main.go:109-115, main.py:737-739)."""
    args = build_parser().parse_args(argv)
    if not args.node_name and args.command not in (
        "get-cc-mode", "probe-devices", "rollout", "fleet-controller",
        "policy-controller", "webhook", "doctor", "simlab",
    ):
        raise SystemExit(
            "NODE_NAME env or --node-name flag is required"
        )
    cfg = AgentConfig(
        node_name=args.node_name or "",
        default_mode=args.default_cc_mode,
        kubeconfig=args.kubeconfig,
        debug=args.debug,
        operator_namespace=os.environ.get("OPERATOR_NAMESPACE", "tpu-system"),
        evict_components=_env_bool("EVICT_OPERATOR_COMPONENTS", True),
        drain_strategy=os.environ.get("DRAIN_STRATEGY", "components"),
        readiness_file=os.environ.get("CC_READINESS_FILE", DEFAULT_READINESS_FILE),
        health_port=args.health_port,
        slice_coordination=_env_bool("SLICE_COORDINATION", False),
        repair_interval_s=float(os.environ.get("REPAIR_INTERVAL_S", "30")),
        trace_file=os.environ.get("CC_TRACE_FILE") or None,
        log_format=os.environ.get("TPU_CC_LOG_FORMAT", "text") or "text",
        flightrec_dir=os.environ.get("TPU_CC_FLIGHTREC_DIR") or None,
        emit_events=_env_bool("EMIT_EVENTS", True),
        emit_evidence=_env_bool("TPU_CC_EVIDENCE", True),
        doctor_interval_s=float(
            os.environ.get("TPU_CC_DOCTOR_INTERVAL_S", "300")
        ),
        slice_commit_timeout_s=float(
            os.environ.get("TPU_CC_SLICE_COMMIT_TIMEOUT_S", "600")
        ),
    )
    return cfg, args
