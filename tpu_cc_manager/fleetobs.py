"""Fleet observatory — scrape, validate, merge, and judge (ISSUE 9).

Every process exposes ``/metrics``; nobody *watches* the fleet. This
module is the controller-side layer that does: it scrapes N replica
expositions (in-process render callables in simlab, HTTP URLs in the
kind smoke), validates each scrape with :func:`obs.validate_exposition`
(an invalid exposition is counted and skipped, never merged), merges
the series fleet-wide (counters/gauges sum; histogram buckets merge
cumulatively with per-input carry-forward, so the aggregate stays
monotone even across bucket-layout drift), re-validates the *merged*
exposition (a merge bug — duplicate series, non-monotone buckets —
must fail as loudly as a replica bug), and feeds a declarative **SLO
engine**.

Objectives live in ``deployments/slo.yaml`` (schema:
:func:`validate_slo_doc`, enforced in the lint tier by ccaudit's
slo pass). Two kinds:

- ``error_ratio``: bad events / total events from counter families
  (e.g. failed reconciles per reconcile, dropped publications per
  reconcile);
- ``latency``: the fraction of histogram observations above
  ``threshold_s`` (good = cumulative count at the largest bucket bound
  <= threshold).

Each objective is judged by **multi-window burn rates** (the
fast/slow-window pattern): ``burn = (bad/total over window) / (1 -
target)``. A burn of 1.0 consumes budget exactly at the sustainable
rate; the alert fires only when BOTH the fast and the slow window
exceed ``burn_threshold`` — fast alone is a blip, slow alone is old
news. Firing emits ``tpu_cc_slo_burn_rate`` / budget gauges, a fleet
``problems`` line, and a flight-recorder ``slo_burn`` event — the
degradation is visible while the convergence gate would still pass.

Budget remaining is computed over the observer's whole retained span:
1 - (observed bad ratio / allowed bad ratio), clamped to [0, 1].
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
import urllib.request
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from tpu_cc_manager.obs import (
    Counter, Gauge, _LABEL_RE, _SAMPLE_RE, _fmt as _num,
    split_exemplar, validate_exposition,
)
from tpu_cc_manager.tsring import (
    Sample, Snapshot, _le_value, counter_delta, window_pair,
)

log = logging.getLogger("tpu-cc-manager.fleetobs")

#: where the objectives live, relative to the repo root
SLO_RELPATH = "deployments/slo.yaml"

#: objective kinds the schema accepts
SLO_KINDS = ("error_ratio", "latency")

#: a scrape source: a callable returning exposition text (in-process)
#: or an http(s) URL string
Source = Union[str, Callable[[], str]]


class SloError(ValueError):
    """An SLO document failed validation."""


# --------------------------------------------------------------- parsing


def parse_exposition(
    text: str,
) -> Tuple[Snapshot, Dict[str, str]]:
    """Parse a (pre-validated) Prometheus text exposition into the
    tsring :data:`Snapshot` shape plus the HELP text per family (the
    merged render re-emits it). Histogram families are reassembled
    from their ``_bucket``/``_sum``/``_count`` series keyed by the
    non-``le`` labelset.

    **Exemplars are STRIPPED here, deterministically** (ISSUE 15
    satellite, the pinned merge policy): a per-replica exemplar names
    ONE process's trace — summing N replicas' buckets has no honest
    single exemplar to carry, and forwarding an arbitrary replica's
    would point a fleet-level bucket at a non-representative trace.
    The merged ``/fleet/metrics`` therefore never emits exemplar
    suffixes; per-trace evidence stays on the replica surfaces (their
    own ``/metrics``) and in the watchdog's incident packets, which
    harvest exemplars from the live per-replica histograms."""
    snap: Snapshot = {}
    helps: Dict[str, str] = {}
    types: Dict[str, str] = {}
    for line in text.splitlines():
        if line.startswith("# HELP "):
            name, _, help_ = line[7:].partition(" ")
            helps[name] = help_
            continue
        if line.startswith("# TYPE "):
            name, _, mtype = line[7:].partition(" ")
            types[name] = mtype
            continue
        if not line or line.startswith("#"):
            continue
        line, _exemplar = split_exemplar(line)  # strip: merge policy
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue  # validate_exposition already reported it
        name, raw_labels = m.group("name"), m.group("labels")
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels: Dict[str, str] = {}
        if raw_labels:
            for lm in _LABEL_RE.finditer(raw_labels):
                labels[lm.group("key")] = lm.group("value")
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and types.get(
                    name[: -len(suffix)]) == "histogram":
                family = name[: -len(suffix)]
                break
        mtype = types.get(family, "untyped")
        if mtype == "histogram":
            fam = snap.setdefault(family, {"type": "histogram", "hist": {}})
            key = ",".join(
                f'{k}="{v}"' for k, v in sorted(labels.items())
                if k != "le"
            )
            hist = fam["hist"].setdefault(
                key, {"buckets": {}, "sum": 0.0, "count": 0}
            )
            if name.endswith("_bucket") and "le" in labels:
                hist["buckets"][labels["le"]] = value
            elif name.endswith("_sum"):
                hist["sum"] = value
            elif name.endswith("_count"):
                hist["count"] = value
        else:
            kind = "counter" if mtype == "counter" else "gauge"
            fam = snap.setdefault(family, {"type": kind, "series": {}})
            key = ",".join(
                f'{k}="{v}"' for k, v in sorted(labels.items())
            )
            fam["series"][key] = value
    return snap, helps


def merge_snapshots(snaps: List[Snapshot]) -> Snapshot:
    """Merge N per-replica snapshots into one fleet snapshot: series
    values sum (counters: fleet totals; gauges: fleet-wide counts);
    histogram buckets merge by ``le`` union with per-input
    carry-forward (an input missing a bound contributes its cumulative
    count at its next-lower bound), which keeps the merged cumulative
    sequence monotone by construction."""
    out: Snapshot = {}
    for snap in snaps:
        for name, fam in snap.items():
            if fam["type"] == "histogram":
                ofam = out.setdefault(
                    name, {"type": "histogram", "hist": {}})
                if "hist" not in ofam:
                    # type drift across replicas (one exposes a
                    # counter, another a histogram, under one name):
                    # first seen wins, the drifted input is skipped —
                    # a mixed merge would be meaningless either way
                    continue
                for key, hist in fam["hist"].items():
                    ohist = ofam["hist"].setdefault(
                        key, {"buckets": {}, "sum": 0.0, "count": 0,
                              "_inputs": []},
                    )
                    ohist["sum"] += hist.get("sum", 0.0)
                    ohist["count"] += hist.get("count", 0)
                    ohist["_inputs"].append(hist.get("buckets") or {})
            else:
                ofam = out.setdefault(
                    name, {"type": fam["type"], "series": {}})
                if "series" not in ofam:
                    continue  # type drift: first seen wins (above)
                for key, v in fam["series"].items():
                    ofam["series"][key] = (
                        ofam["series"].get(key, 0.0) + v
                    )
    # second pass: fold each histogram's inputs over the le union
    for fam in out.values():
        if fam["type"] != "histogram":
            continue
        for hist in fam["hist"].values():
            inputs = hist.pop("_inputs", [])
            les = sorted(
                {le for b in inputs for le in b}, key=_le_value
            )
            merged: Dict[str, float] = {}
            carry = [0.0] * len(inputs)
            for le in les:
                total = 0.0
                for i, b in enumerate(inputs):
                    if le in b:
                        carry[i] = max(b[le], carry[i])
                    total += carry[i]
                merged[le] = total
            hist["buckets"] = merged
    return out


def render_snapshot(
    snap: Snapshot, helps: Optional[Dict[str, str]] = None,
) -> str:
    """Render a (merged) snapshot back to Prometheus text format —
    one HELP/TYPE per family, series sorted, buckets in ``le`` order.
    The output must itself pass :func:`obs.validate_exposition`; the
    observer re-checks that on every merge (ISSUE 9 satellite: a
    256-replica merge must not emit duplicate series or non-monotone
    buckets)."""
    helps = helps or {}
    lines: List[str] = []
    for name in sorted(snap):
        fam = snap[name]
        help_ = helps.get(name, "aggregated across fleet replicas")
        if fam["type"] == "histogram":
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} histogram")
            for key in sorted(fam["hist"]):
                hist = fam["hist"][key]
                prefix = key + "," if key else ""
                for le in sorted(hist["buckets"], key=_le_value):
                    lines.append(
                        f'{name}_bucket{{{prefix}le="{le}"}} '
                        f'{_num(hist["buckets"][le])}'
                    )
                suffix = "{" + key + "}" if key else ""
                lines.append(f"{name}_sum{suffix} {_num(hist['sum'])}")
                lines.append(
                    f"{name}_count{suffix} {_num(hist['count'])}"
                )
        else:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {fam['type']}")
            for key in sorted(fam["series"]):
                braces = "{" + key + "}" if key else ""
                lines.append(
                    f"{name}{braces} {_num(fam['series'][key])}"
                )
    return "\n".join(lines) + "\n"



def _series_labels(key: str) -> Dict[str, str]:
    return {
        m.group("key"): m.group("value")
        for m in _LABEL_RE.finditer(key)
    }


# ------------------------------------------------------------ objectives


@dataclasses.dataclass(frozen=True)
class SloObjective:
    name: str
    kind: str  #: "error_ratio" | "latency"
    metric: str
    target: float  #: good fraction the objective promises, in (0, 1)
    fast_window_s: float
    slow_window_s: float
    burn_threshold: float
    description: str = ""
    #: error_ratio: label -> bad values; empty = every series of
    #: ``metric`` is a bad event (then ``total_metric`` is required)
    bad_labels: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()
    #: error_ratio: denominator family (default: ``metric`` itself)
    total_metric: Optional[str] = None
    #: latency: observations above this bound are bad events
    threshold_s: Optional[float] = None

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def metric_refs(self) -> List[str]:
        refs = [self.metric]
        if self.total_metric:
            refs.append(self.total_metric)
        return refs


def _require(cond: bool, where: str, msg: str,
             errors: List[str]) -> bool:
    if not cond:
        errors.append(f"{where}: {msg}")
    return cond


def validate_slo_doc(doc: object) -> Tuple[List[SloObjective], List[str]]:
    """Strict schema validation of a parsed slo.yaml document ->
    (objectives, errors). Unknown keys anywhere are errors — the same
    stance the simlab scenario schema takes, and what lets the lint
    tier gate the committed file."""
    errors: List[str] = []
    objectives: List[SloObjective] = []
    if not isinstance(doc, dict):
        return [], ["slo document must be a mapping"]
    unknown = sorted(set(doc) - {"version", "objectives"})
    if unknown:
        errors.append(f"unknown top-level key(s) {unknown}")
    if doc.get("version") != 1:
        errors.append(
            f"version must be 1, got {doc.get('version')!r}"
        )
    raw = doc.get("objectives")
    if not isinstance(raw, list) or not raw:
        errors.append("objectives is required and must be a non-empty list")
        return [], errors
    seen_names = set()
    allowed = {
        "name", "description", "kind", "metric", "bad_labels",
        "total_metric", "threshold_s", "target", "windows",
        "burn_threshold",
    }
    for idx, o in enumerate(raw):
        where = f"objectives[{idx}]"
        if not isinstance(o, dict):
            errors.append(f"{where}: must be a mapping")
            continue
        unknown = sorted(set(o) - allowed)
        if unknown:
            errors.append(f"{where}: unknown key(s) {unknown}")
        name = o.get("name")
        if not _require(isinstance(name, str) and bool(name), where,
                        "name is required", errors):
            continue
        where = f"objectives[{idx}] ({name})"
        if name in seen_names:
            errors.append(f"{where}: duplicate objective name")
        seen_names.add(name)
        kind = o.get("kind")
        if not _require(kind in SLO_KINDS, where,
                        f"kind must be one of {list(SLO_KINDS)}",
                        errors):
            continue
        metric = o.get("metric")
        if not _require(isinstance(metric, str) and bool(metric),
                        where, "metric is required", errors):
            continue
        target = o.get("target")
        if not _require(
            isinstance(target, (int, float))
            and not isinstance(target, bool) and 0.0 < target < 1.0,
            where, "target must be a number in (0, 1)", errors,
        ):
            continue
        windows = o.get("windows")
        if not _require(isinstance(windows, dict), where,
                        "windows {fast_s, slow_s} is required", errors):
            continue
        unknown = sorted(set(windows) - {"fast_s", "slow_s"})
        if unknown:
            errors.append(f"{where}: windows has unknown key(s) {unknown}")
        fast = windows.get("fast_s")
        slow = windows.get("slow_s")
        ok = _require(
            isinstance(fast, (int, float)) and fast > 0
            and isinstance(slow, (int, float)) and slow > 0
            and not isinstance(fast, bool)
            and not isinstance(slow, bool),
            where, "windows.fast_s and windows.slow_s must be > 0",
            errors,
        )
        if ok and not fast < slow:
            errors.append(f"{where}: fast_s must be < slow_s")
            ok = False
        burn = o.get("burn_threshold")
        ok &= _require(
            isinstance(burn, (int, float))
            and not isinstance(burn, bool) and burn >= 1.0, where,
            "burn_threshold must be a number >= 1 (1.0 = exactly "
            "sustainable burn)", errors,
        )
        bad_labels: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()
        total_metric = o.get("total_metric")
        threshold_s = o.get("threshold_s")
        if kind == "error_ratio":
            raw_bad = o.get("bad_labels")
            if raw_bad is not None:
                if not isinstance(raw_bad, dict) or not all(
                    isinstance(k, str) and isinstance(v, list)
                    and all(isinstance(x, str) for x in v)
                    for k, v in raw_bad.items()
                ):
                    errors.append(
                        f"{where}: bad_labels must map label -> "
                        "list of bad string values")
                    ok = False
                else:
                    bad_labels = tuple(
                        (k, tuple(v)) for k, v in sorted(raw_bad.items())
                    )
            if raw_bad is None and total_metric is None:
                errors.append(
                    f"{where}: error_ratio needs bad_labels (bad "
                    "subset of metric) or total_metric (metric counts "
                    "bad events, total_metric the denominator)")
                ok = False
            if total_metric is not None and not isinstance(
                    total_metric, str):
                errors.append(f"{where}: total_metric must be a string")
                ok = False
            if threshold_s is not None:
                errors.append(
                    f"{where}: threshold_s only applies to kind=latency")
                ok = False
        else:  # latency
            if not isinstance(threshold_s, (int, float)) or isinstance(
                    threshold_s, bool) or threshold_s <= 0:
                errors.append(
                    f"{where}: latency needs threshold_s > 0")
                ok = False
            if o.get("bad_labels") is not None or total_metric is not None:
                errors.append(
                    f"{where}: bad_labels/total_metric only apply to "
                    "kind=error_ratio")
                ok = False
        if not ok:
            continue
        objectives.append(SloObjective(
            name=name, kind=kind, metric=metric,
            target=float(target),
            fast_window_s=float(fast), slow_window_s=float(slow),
            burn_threshold=float(burn),
            description=str(o.get("description", "")),
            bad_labels=bad_labels,
            total_metric=total_metric,
            threshold_s=(
                float(threshold_s) if threshold_s is not None else None
            ),
        ))
    return objectives, errors


def load_slo(path: str) -> List[SloObjective]:
    """Load + validate ``slo.yaml``. Raises :class:`SloError` on any
    schema violation (the lint tier runs the same validation through
    ccaudit, so a committed file that raises here fails CI first) and
    ImportError when pyyaml is unavailable (callers degrade loudly)."""
    import yaml

    try:
        with open(path) as f:
            doc = yaml.safe_load(f)
    except OSError as e:
        raise SloError(f"cannot read {path}: {e}") from e
    except yaml.YAMLError as e:
        raise SloError(f"{path}: not valid YAML: {e}") from e
    objectives, errors = validate_slo_doc(doc)
    if errors:
        raise SloError(f"{path}: " + "; ".join(errors))
    return objectives


def default_slo_path() -> str:
    """``deployments/slo.yaml`` resolved from the package location
    (works from any cwd), overridable via ``TPU_CC_SLO_FILE``."""
    return os.environ.get("TPU_CC_SLO_FILE") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        *SLO_RELPATH.split("/"),
    )


# --------------------------------------------------------------- metrics


class SloMetrics:
    """The observer's own metric set (rendered by reflection like
    every other set — obs.registered_metrics)."""

    def __init__(self) -> None:
        self.burn_rate = Gauge(
            "tpu_cc_slo_burn_rate",
            "Error-budget burn rate per objective and window (1.0 = "
            "burning exactly at the sustainable rate)",
            ("objective", "window"),
        )
        self.budget_remaining = Gauge(
            "tpu_cc_slo_budget_remaining",
            "Fraction of the objective's error budget left over the "
            "observer's retained span (1.0 = untouched)",
            ("objective",),
        )
        self.scrapes_total = Counter(
            "tpu_cc_fleetobs_scrapes_total",
            "Replica exposition scrapes, by outcome (invalid = "
            "failed obs.validate_exposition and was NOT merged)",
            ("outcome",),
        )
        self.aggregation_invalid_total = Counter(
            "tpu_cc_fleetobs_aggregation_invalid_total",
            "Merged fleet expositions that failed validation (a merge "
            "bug: duplicate series or non-monotone buckets)",
        )
        self.alerts_total = Counter(
            "tpu_cc_slo_alerts_total",
            "Multi-window burn-rate alerts fired, per objective",
            ("objective",),
        )

    def render(self) -> str:
        from tpu_cc_manager.obs import render_metric_set

        return render_metric_set(self)


# -------------------------------------------------------------- observer


class FleetObserver:
    """Scrape N sources, merge, evaluate the SLOs, keep the history."""

    DEFAULT_INTERVAL_S = 1.0

    def __init__(
        self,
        objectives: List[SloObjective],
        *,
        name: str = "fleetobs",
        recorder: Optional[Any] = None,
        interval_s: Optional[float] = None,
        capacity: int = 512,
    ):
        if interval_s is None:
            try:
                interval_s = float(os.environ.get(
                    "TPU_CC_FLEETOBS_INTERVAL_S", "") or 0)
            except ValueError:
                interval_s = 0.0
            if interval_s <= 0:
                interval_s = self.DEFAULT_INTERVAL_S
        self.name = name
        self.objectives = list(objectives)
        self.interval_s = interval_s
        #: flight recorder the ``slo_burn`` alert events note into
        self.recorder = recorder
        self.metrics = SloMetrics()
        self._samples: "deque[Sample]" = deque(maxlen=capacity)
        self._helps: Dict[str, str] = {}
        self._lock = threading.Lock()
        #: serializes _evaluate: the runner's closing observe() racing
        #: the scrape loop must not double-fire one alert transition
        self._eval_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sources: List[Source] = []
        #: objective name -> why it can never fire (kind/metric-type
        #: mismatch observed at evaluation time) — a dead objective
        #: must be a problems line, not silence
        self._misconfigured: Dict[str, str] = {}
        #: objective name -> currently firing (multi-window rule)
        self._firing: Dict[str, bool] = {}
        #: alert log: one entry per not-firing -> firing transition
        self.alerts: List[Dict[str, Any]] = []
        #: problems from the last AGGREGATED-exposition validation
        self.aggregation_problems: List[str] = []
        #: last merged snapshot (for render())
        self._last_merged: Optional[Snapshot] = None
        #: post-sample listeners (the fleet-level anomaly watchdog,
        #: ISSUE 15): fn(samples) after every observe() pass — the
        #: fleet-merged series ride the same window machinery a
        #: per-process tsring feeds
        self._listeners: List[Callable[[List[Sample]], Any]] = []

    def add_listener(
        self, fn: Callable[[List[Sample]], Any],
    ) -> "FleetObserver":
        self._listeners.append(fn)
        return self

    def samples(self) -> List[Sample]:
        """The retained (ts, merged snapshot) history — tsring sample
        shape, so window math and the watchdog consume it as-is."""
        with self._lock:
            return list(self._samples)

    # ------------------------------------------------------------ scraping
    def _fetch(self, source: Source) -> str:
        if callable(source):
            return source()
        with urllib.request.urlopen(source, timeout=5) as r:
            return r.read().decode()

    def scrape(self, sources: List[Source]) -> Snapshot:
        """One scrape pass: fetch + validate every source, merge the
        valid ones. Invalid/unreachable sources are counted and
        skipped — one broken replica must not poison the rollup."""
        parsed: List[Snapshot] = []
        for source in sources:
            try:
                text = self._fetch(source)
            except Exception:  # ccaudit: allow-swallow(an unreachable scrape target is an expected fleet condition: counted in tpu_cc_fleetobs_scrapes_total{outcome="unreachable"} and skipped — the rollup must carry on with the replicas that answered)
                self.metrics.scrapes_total.inc("unreachable")
                continue
            problems = validate_exposition(text)
            if problems:
                self.metrics.scrapes_total.inc("invalid")
                log.warning(
                    "fleetobs: invalid exposition from %r skipped "
                    "(%d problem(s); first: %s)",
                    getattr(source, "__name__", source),
                    len(problems), problems[0],
                )
                continue
            snap, helps = parse_exposition(text)
            self._helps.update(helps)
            parsed.append(snap)
            self.metrics.scrapes_total.inc("ok")
        return merge_snapshots(parsed)

    def observe(
        self, sources: List[Source], now: Optional[float] = None,
    ) -> Snapshot:
        """Scrape, validate the AGGREGATE, record the sample, evaluate
        every objective. The merged-exposition validation is the ISSUE
        9 satellite: merging 256 replicas must yield an exposition as
        strict as any single process's."""
        merged = self.scrape(sources)
        problems = validate_exposition(
            render_snapshot(merged, self._helps)
        )
        if problems:
            self.metrics.aggregation_invalid_total.inc()
            log.warning(
                "fleetobs: MERGED exposition invalid (%d problem(s); "
                "first: %s)", len(problems), problems[0],
            )
        ts = now if now is not None else time.time()
        with self._lock:
            self.aggregation_problems = problems
            self._last_merged = merged
            self._samples.append((ts, merged))
            samples = list(self._samples)
        self._evaluate(samples, ts)
        for fn in self._listeners:
            try:
                fn(samples)
            except Exception:  # ccaudit: allow-swallow(a broken listener must cost itself, never the scrape loop; the warning names it)
                log.warning("fleetobs listener failed", exc_info=True)
        return merged

    # ---------------------------------------------------------- SLO engine
    def _bad_total(
        self, obj: SloObjective, snap: Snapshot,
    ) -> Tuple[float, float]:
        """(bad events, total events) cumulative in one snapshot."""
        fam = snap.get(obj.metric) or {}
        if fam and obj.kind == "latency" and "hist" not in fam:
            self._note_misconfigured(
                obj, f"metric {obj.metric!r} is a "
                f"{fam.get('type')}, not a histogram")
        if fam and obj.kind == "error_ratio" and "series" not in fam:
            self._note_misconfigured(
                obj, f"metric {obj.metric!r} is a histogram; "
                "error_ratio needs a counter family")
        if obj.kind == "latency":
            bad = total = 0.0
            threshold = obj.threshold_s or 0.0
            for hist in (fam.get("hist") or {}).values():
                buckets = hist.get("buckets") or {}
                count = float(hist.get("count", 0))
                good = 0.0
                for le in sorted(buckets, key=_le_value):
                    bound = _le_value(le)
                    if bound <= threshold:
                        good = max(good, buckets[le])
                total += count
                bad += max(count - good, 0.0)
            return bad, total
        bad = 0.0
        metric_total = 0.0
        bad_labels = dict(obj.bad_labels)
        for key, value in (fam.get("series") or {}).items():
            metric_total += value
            labels = _series_labels(key)
            if bad_labels:
                if all(labels.get(k) in vals
                       for k, vals in bad_labels.items()):
                    bad += value
            else:
                bad += value  # whole family counts bad events
        if obj.total_metric:
            tfam = snap.get(obj.total_metric) or {}
            if tfam and "series" not in tfam:
                self._note_misconfigured(
                    obj, f"total_metric {obj.total_metric!r} is a "
                    "histogram; the denominator must be a counter "
                    "family")
            total = sum(tfam.get("series", {}).values())
        else:
            total = metric_total
        return bad, total

    def _window_burn(
        self, obj: SloObjective, samples: List[Sample],
        window_s: float, now: float,
    ) -> float:
        pair = window_pair(samples, window_s, now=now)
        if pair is None:
            return 0.0
        (_, old_snap), (_, new_snap) = pair
        old_bad, old_total = self._bad_total(obj, old_snap)
        new_bad, new_total = self._bad_total(obj, new_snap)
        d_bad = counter_delta(old_bad, new_bad)
        d_total = counter_delta(old_total, new_total)
        if d_total <= 0:
            return 0.0
        return (d_bad / d_total) / obj.budget

    def _note_misconfigured(self, obj: SloObjective,
                            reason: str) -> None:
        """A schema-valid objective whose metric TYPE can't answer its
        kind (error_ratio over a histogram, latency over a counter)
        evaluates to a permanent 0 — the alert-that-can-never-fire
        failure class. Validation can't see types; the first evaluation
        can, so it records the finding for problems()/summary()."""
        if obj.name not in self._misconfigured:
            self._misconfigured[obj.name] = reason
            log.warning("SLO %s is DEAD: %s", obj.name, reason)

    def _evaluate(self, samples: List[Sample], now: float) -> None:
        with self._eval_lock:
            for obj in self.objectives:
                fast = self._window_burn(obj, samples, obj.fast_window_s, now)
                slow = self._window_burn(obj, samples, obj.slow_window_s, now)
                self.metrics.burn_rate.set(round(fast, 4), obj.name, "fast")
                self.metrics.burn_rate.set(round(slow, 4), obj.name, "slow")
                # budget over the whole RETAINED SPAN (first sample ->
                # latest), not the replicas' process lifetimes: the
                # counters are cumulative, so judging the raw latest
                # ratio would charge this observer for events before it
                # started watching (exactly what simlab's
                # start-after-initial-convergence exists to exclude)
                # and a single early incident would depress the gauge
                # forever on a long-lived deployment
                bad0, total0 = self._bad_total(obj, samples[0][1])
                bad1, total1 = self._bad_total(obj, samples[-1][1])
                d_bad = counter_delta(bad0, bad1)
                d_total = counter_delta(total0, total1)
                consumed = (
                    (d_bad / d_total) / obj.budget
                    if d_total > 0 else 0.0
                )
                remaining = min(max(1.0 - consumed, 0.0), 1.0)
                self.metrics.budget_remaining.set(
                    round(remaining, 4), obj.name)
                firing = (fast >= obj.burn_threshold
                          and slow >= obj.burn_threshold)
                was = self._firing.get(obj.name, False)
                self._firing[obj.name] = firing
                if firing and not was:
                    self.metrics.alerts_total.inc(obj.name)
                    entry = {
                        "at": round(now, 3),
                        "objective": obj.name,
                        "fast_burn": round(fast, 3),
                        "slow_burn": round(slow, 3),
                        "budget_remaining": round(remaining, 4),
                    }
                    with self._lock:
                        self.alerts.append(entry)
                    log.warning(
                        "SLO %s burning: fast %.1fx / slow %.1fx over the "
                        "%.1fx threshold (budget remaining %.1f%%)",
                        obj.name, fast, slow, obj.burn_threshold,
                        remaining * 100,
                    )
                    if self.recorder is not None:
                        # the alert lands in the flight-recorder dump —
                        # the black box says WHEN the budget burned
                        self.recorder.note("slo_burn", **entry)

    # ------------------------------------------------------------- reading
    def problems(self) -> List[str]:
        """Fleet ``problems`` lines for currently-burning objectives
        (joined into the fleet controller's report digest) plus any
        aggregation-validity finding."""
        out = []
        for obj in self.objectives:
            if self._firing.get(obj.name):
                fast = self.metrics.burn_rate.value(obj.name, "fast")
                remaining = self.metrics.budget_remaining.value(obj.name)
                out.append(
                    f"SLO {obj.name} burning error budget at "
                    f"{fast or 0:.1f}x the sustainable rate "
                    f"({(remaining or 0) * 100:.1f}% budget left)"
                )
        for name, reason in sorted(self._misconfigured.items()):
            out.append(
                f"SLO {name} can never fire: {reason} — fix the "
                "objective's kind or metric"
            )
        with self._lock:
            if self.aggregation_problems:
                out.append(
                    "fleet metrics aggregation invalid: "
                    f"{len(self.aggregation_problems)} problem(s); "
                    f"first: {self.aggregation_problems[0]}"
                )
        return out

    def status(self) -> Dict[str, Any]:
        """Small per-objective digest for /report."""
        out: Dict[str, Any] = {}
        for obj in self.objectives:
            out[obj.name] = {
                "burning": bool(self._firing.get(obj.name)),
                "fast_burn": self.metrics.burn_rate.value(
                    obj.name, "fast"),
                "slow_burn": self.metrics.burn_rate.value(
                    obj.name, "slow"),
                "budget_remaining": self.metrics.budget_remaining.value(
                    obj.name),
            }
        return out

    def summary(self) -> Dict[str, Any]:
        """The artifact block (simlab) / debug surface: objectives,
        alert log, scrape accounting, aggregation validity."""
        with self._lock:
            alerts = list(self.alerts)
            agg_problems = list(self.aggregation_problems)
            n_samples = len(self._samples)
        return {
            "objectives": self.status(),
            "alerts": alerts,
            "samples": n_samples,
            "scrapes": {
                outcome: self.metrics.scrapes_total.value(outcome)
                for outcome in ("ok", "invalid", "unreachable")
            },
            "aggregation_problems": agg_problems,
            "misconfigured": dict(sorted(self._misconfigured.items())),
        }

    def render(self) -> str:
        """The fleet rollup exposition: the merged replica series plus
        the observer's own SLO/scrape metrics (disjoint family names,
        so the concatenation is itself a valid exposition)."""
        with self._lock:
            merged = self._last_merged
            helps = dict(self._helps)
        body = render_snapshot(merged, helps) if merged else ""
        return body + self.metrics.render()

    # ---------------------------------------------------------------- loop
    def start(self, sources: List[Source]) -> "FleetObserver":
        """Periodic scrape loop (daemon; idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._sources = sources
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"fleetobs-{self.name}", daemon=True,
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.observe(self._sources)
            except Exception:  # ccaudit: allow-swallow(the scrape loop must survive any single pass failing — a malformed source or a transient socket error costs one sample, and the warning names it)
                log.warning("fleetobs observe pass failed",
                            exc_info=True)
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)
