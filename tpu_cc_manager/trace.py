"""Reconcile tracing — per-phase spans with durable export.

The reference's only "tracing" is ``set -x`` on its bash engine
(SURVEY.md §5.1: every command echoed to the pod log, nothing structured,
nothing timed). This module is the deliberate improvement SURVEY.md §7.2
step 5 calls for: every reconcile becomes a tree of timed spans
(enumerate → plan → evict → per-device flip → reschedule), so the
wall-clock dominators the reference can only be guessed at from logs —
eviction pod-waits and device reset/boot (SURVEY.md §3.5) — are measured
per phase, per device.

Design:

- :class:`Tracer` keeps a thread-local span stack (nesting without
  explicit parent plumbing) and a bounded ring of completed spans.
  Work handed to another thread keeps its place in the tree via
  :meth:`Tracer.current_span` (capture on the submitting thread) +
  :meth:`Tracer.adopt` (re-seat on the worker) — the parallel flip
  pipeline's per-device spans nest under the reconcile exactly as the
  serial loop's did.
- Sinks observe every completed span: :class:`JsonlSink` appends one JSON
  line per span to ``CC_TRACE_FILE`` (the structured replacement for
  ``set -x``); the agent adds a metrics sink so ``/metrics`` exports a
  per-phase duration histogram; ``/debug/traces`` on the health server
  serves the ring for live inspection.
- Tracing is always on (it is microseconds of overhead per reconcile);
  sinks are what you opt into.

The span vocabulary (``PHASES``) is intentionally closed: the per-phase
histogram's label cardinality stays bounded no matter what attrs
individual spans carry.
"""

from __future__ import annotations

import itertools
import json
import logging
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, Iterator, List, Optional

log = logging.getLogger("tpu-cc-manager.trace")

#: Closed span-name vocabulary (metrics label values).
PHASES = (
    "reconcile",    # root: one desired-mode application end to end
    "enumerate",    # device discovery
    "plan",         # divergence computation
    "slice_wait",   # slice-coordination wait for quorum commit
    "evict",        # L2 drain
    "flip",         # one device: stage + reset + wait + verify
    "stage",        # flip sub-phase: discard stale + stage domains
    "reset",        # flip sub-phase: the device reset itself
    "wait_ready",   # flip sub-phase: post-reset boot wait
    "verify",       # flip sub-phase: query-back + independent verify
    "reschedule",   # L2 restore
    "state_label",  # observed-state label publish
)


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "start_ts", "dur_s", "status", "error", "attrs",
    )

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict[str, object]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ts = time.time()
        self.dur_s: float = 0.0
        self.status = "ok"
        self.error: Optional[str] = None
        self.attrs = attrs

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "trace": self.trace_id,
            "span": self.span_id,
            "start_ts": round(self.start_ts, 6),
            "dur_s": round(self.dur_s, 6),
            "status": self.status,
        }
        if self.parent_id is not None:
            d["parent"] = self.parent_id
        if self.error is not None:
            d["error"] = self.error
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class Tracer:
    """Thread-safe span recorder. One process-wide instance is enough; the
    thread-local stack keeps concurrent threads' span trees separate."""

    def __init__(self, ring_size: int = 2048):
        self._ring: deque = deque(maxlen=ring_size)
        self._sinks: List[Callable[[Span], None]] = []
        self._local = threading.local()
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    # ------------------------------------------------------------- plumbing
    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    def _next_id(self) -> str:
        with self._lock:
            return format(next(self._ids), "x")

    def add_sink(self, sink: Callable[[Span], None]) -> "Tracer":
        self._sinks.append(sink)
        return self

    def current_span(self) -> Optional[Span]:
        """The innermost open span on THIS thread (None at top level).
        Capture it before submitting work to another thread and hand it
        to :meth:`adopt` there — cross-thread span parenting."""
        stack = self._stack()
        return stack[-1] if stack else None

    @contextmanager
    def adopt(self, parent: Optional[Span]) -> Iterator[None]:
        """Make ``parent`` (captured via :meth:`current_span` on another
        thread) the current span for this thread while the context is
        active: spans opened inside nest under it — same trace id,
        ``parent_id=parent.span_id`` — exactly as if they ran on the
        submitting thread. The parent span object is only *read* here
        (its ids), so adopting a still-open span owned by another thread
        is safe. No-op when ``parent`` is None (untraced caller)."""
        if parent is None:
            yield
            return
        stack = self._stack()
        stack.append(parent)
        try:
            yield
        finally:
            stack.pop()

    # --------------------------------------------------------------- spans
    @contextmanager
    def span(self, name: str, **attrs: object) -> Iterator[Span]:
        """Time a phase. Exceptions mark the span failed and propagate."""
        stack = self._stack()
        parent = stack[-1] if stack else None
        sid = self._next_id()
        s = Span(
            name,
            trace_id=parent.trace_id if parent else sid,
            span_id=sid,
            parent_id=parent.span_id if parent else None,
            attrs=attrs,
        )
        t0 = time.monotonic()
        stack.append(s)
        try:
            yield s
        except BaseException as e:
            s.status = "error"
            s.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            s.dur_s = time.monotonic() - t0
            stack.pop()
            self._record(s)

    def _record(self, s: Span) -> None:
        with self._lock:
            self._ring.append(s)
        for sink in self._sinks:
            try:
                sink(s)
            except Exception:  # a broken sink must never break a reconcile
                log.exception("trace sink failed")

    # ------------------------------------------------------------- reading
    def recent(self, limit: int = 256) -> List[dict]:
        """Most recent completed spans, oldest first."""
        with self._lock:  # snapshot: reconcile threads append concurrently
            items = list(self._ring)
        return [s.to_dict() for s in items[-limit:]]

    def traces(self, limit: int = 16) -> List[List[dict]]:
        """Recent spans grouped by trace id, oldest trace first."""
        with self._lock:
            items = list(self._ring)
        by_trace: Dict[str, List[dict]] = {}
        for s in items:
            by_trace.setdefault(s.trace_id, []).append(s.to_dict())
        return list(by_trace.values())[-limit:]


class JsonlSink:
    """Append one JSON line per completed span to a file — the structured
    successor of the bash engine's ``set -x`` log. Enable with
    ``CC_TRACE_FILE=/var/log/tpu-cc-trace.jsonl``."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def __call__(self, span: Span) -> None:
        line = json.dumps(span.to_dict(), sort_keys=True)
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")


_default = Tracer()


def get_tracer() -> Tracer:
    return _default


def set_tracer(tracer: Optional[Tracer]) -> None:
    """Swap the process-wide tracer (tests use this for isolation)."""
    global _default
    _default = tracer or Tracer()
