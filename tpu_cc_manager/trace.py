"""Reconcile tracing — per-phase spans with durable export.

The reference's only "tracing" is ``set -x`` on its bash engine
(SURVEY.md §5.1: every command echoed to the pod log, nothing structured,
nothing timed). This module is the deliberate improvement SURVEY.md §7.2
step 5 calls for: every reconcile becomes a tree of timed spans
(enumerate → plan → evict → per-device flip → reschedule), so the
wall-clock dominators the reference can only be guessed at from logs —
eviction pod-waits and device reset/boot (SURVEY.md §3.5) — are measured
per phase, per device.

Design:

- :class:`Tracer` keeps a thread-local span stack (nesting without
  explicit parent plumbing) and a bounded ring of completed spans.
  Work handed to another thread keeps its place in the tree via
  :meth:`Tracer.current_span` (capture on the submitting thread) +
  :meth:`Tracer.adopt` (re-seat on the worker) — the parallel flip
  pipeline's per-device spans nest under the reconcile exactly as the
  serial loop's did.
- Sinks observe every completed span: :class:`JsonlSink` appends one JSON
  line per span to ``CC_TRACE_FILE`` (the structured replacement for
  ``set -x``; size-capped via ``TPU_CC_TRACE_JSONL_MAX_MB``); the agent
  adds a metrics sink so ``/metrics`` exports a per-phase duration
  histogram; ``/debug/traces`` on the health server serves the ring for
  live inspection.
- Tracing is always on (it is microseconds of overhead per reconcile);
  sinks are what you opt into.
- **Cross-process propagation** (ISSUE 8): :func:`format_traceparent`
  renders an open span as a W3C-traceparent-style string
  (``00-<trace>-<span>-01``) that rides the
  ``tpu.google.com/cc.trace`` node annotation in the SAME write as the
  desired-mode label; :meth:`Tracer.adopt_remote` re-seats the parsed
  context on the consuming process's thread, so the agent's reconcile
  tree carries the controller's trace id. Span ids carry a per-tracer
  random prefix so independently-minted traces from different
  processes (or different tracers in one process) never collide when a
  collector stitches them by trace id.

The span vocabulary (``PHASES``) is intentionally closed: the per-phase
histogram's label cardinality stays bounded no matter what attrs
individual spans carry.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, Iterator, List, Optional, Tuple

log = logging.getLogger("tpu-cc-manager.trace")

#: fixed version field of the traceparent-style context string
TRACEPARENT_VERSION = "00"

# Innermost OPEN span per thread across ALL tracer instances — the
# join key structured logging needs (obs.JsonLogFormatter): the agent,
# simlab replicas, and controllers each run their own Tracer, and a
# log record must find "the span I am inside" without knowing which
# tracer opened it. Maintained by Tracer.span/adopt/adopt_remote.
_active = threading.local()

# The same per-thread stacks, readable from OTHER threads: the sampling
# profiler (profiler.py, ISSUE 15) keys each wall-clock sample to the
# span active on the sampled thread, and a thread-local is invisible
# across threads. Each thread's stack LIST is registered here once (on
# its first span); registration and pruning happen under _registry_lock,
# while the sampler reads bare dict lookups + list[-1] — both atomic
# under the GIL, and a sampler that sees a stale entry (a reused ident
# whose new thread has not opened a span yet) reads an empty stack.
_registry_lock = threading.Lock()
_thread_stacks: Dict[int, List["Span"]] = {}
_REGISTRY_PRUNE_AT = 512


def _active_stack() -> List["Span"]:
    stack = getattr(_active, "stack", None)
    if stack is None:
        stack = _active.stack = []
        with _registry_lock:
            if len(_thread_stacks) >= _REGISTRY_PRUNE_AT:
                live = {t.ident for t in threading.enumerate()}
                for dead in [i for i in _thread_stacks
                             if i not in live]:
                    del _thread_stacks[dead]
            _thread_stacks[threading.get_ident()] = stack
    return stack


def span_on_thread(ident: int) -> Optional["Span"]:
    """The innermost open span on the thread with OS ident ``ident``
    (None when that thread is outside any span, or has never opened
    one). Sampling-grade by design: the read is lock-free and a span
    closing concurrently may still be returned for one sample — fine
    for a profiler, wrong for anything that needs a fence."""
    stack = _thread_stacks.get(ident)  # ccaudit: allow-race-lockset(sampler-grade read: dict get + list[-1] are GIL-atomic; registration is lock-guarded and a stale/racing entry costs one mis-keyed sample, never a crash)
    try:
        return stack[-1] if stack else None
    except IndexError:
        # the owning thread popped its last span between the check and
        # the index — the span just closed, so "no active span" is the
        # true answer (and an escaped IndexError would kill the armed
        # sampler thread permanently)
        return None


def active_span() -> Optional["Span"]:
    """The innermost open span on THIS thread, whichever tracer opened
    it (None at top level)."""
    stack = getattr(_active, "stack", None)
    return stack[-1] if stack else None


def current_trace_ids() -> Tuple[Optional[str], Optional[str]]:
    """(trace_id, span_id) of the active span — (None, None) outside
    any span. The one key logs and traces join on."""
    span = active_span()
    if span is None:
        return None, None
    return span.trace_id, span.span_id


class RemoteContext:
    """A parsed cross-process trace context: just the two ids
    :meth:`Tracer.adopt` needs to re-seat a remote parent."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id


def format_traceparent(span: "Span") -> str:
    """Render ``span`` as the cc.trace annotation value:
    ``00-<trace>-<span>-01`` (W3C traceparent shape with this build's
    counter-style ids). Safe on an OPEN span — ids are assigned at
    creation."""
    return f"{TRACEPARENT_VERSION}-{span.trace_id}-{span.span_id}-01"


def parse_traceparent(value: Optional[str]) -> Optional[RemoteContext]:
    """Parse an annotation value back into a context; None for
    missing/garbled input (a node-writable annotation is hostile
    surface — bad context degrades to a local trace, never throws)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.split("-")
    if len(parts) != 4 or parts[0] != TRACEPARENT_VERSION:
        return None
    _, trace_id, span_id, _ = parts
    if not trace_id or not span_id:
        return None
    return RemoteContext(trace_id, span_id)

#: Closed span-name vocabulary (metrics label values).
PHASES = (
    "desired_write",  # controller/driver root: desired-mode label commit
    "reconcile",    # root: one desired-mode application end to end
    "enumerate",    # device discovery
    "plan",         # divergence computation
    "slice_wait",   # slice-coordination wait for quorum commit
    "evict",        # L2 drain
    "flip",         # one device: stage + reset + wait + verify
    "stage",        # flip sub-phase: discard stale + stage domains
    "reset",        # flip sub-phase: the device reset itself
    "wait_ready",   # flip sub-phase: post-reset boot wait
    "verify",       # flip sub-phase: query-back + independent verify
    "reschedule",   # L2 restore
    "state_label",  # observed-state label publish
)


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "start_ts", "dur_s", "status", "error", "attrs",
    )

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict[str, object]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ts = time.time()
        self.dur_s: float = 0.0
        self.status = "ok"
        self.error: Optional[str] = None
        self.attrs = attrs

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "trace": self.trace_id,
            "span": self.span_id,
            "start_ts": round(self.start_ts, 6),
            "dur_s": round(self.dur_s, 6),
            "status": self.status,
        }
        if self.parent_id is not None:
            d["parent"] = self.parent_id
        if self.error is not None:
            d["error"] = self.error
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class Tracer:
    """Thread-safe span recorder. One process-wide instance is enough; the
    thread-local stack keeps concurrent threads' span trees separate."""

    def __init__(self, ring_size: int = 2048):
        self._ring: deque = deque(maxlen=ring_size)
        self._sinks: List[Callable[[Span], None]] = []
        self._local = threading.local()
        self._ids = itertools.count(1)
        # random per-tracer prefix: ids minted by DIFFERENT tracers
        # (two processes, or the agent's tracer vs a controller's in
        # one simlab process) must never collide once a collector
        # stitches spans fleet-wide by trace id
        self._id_prefix = os.urandom(4).hex()
        self._lock = threading.Lock()

    # ------------------------------------------------------------- plumbing
    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    def _next_id(self) -> str:
        with self._lock:
            return f"{self._id_prefix}{next(self._ids):x}"

    def add_sink(self, sink: Callable[[Span], None]) -> "Tracer":
        self._sinks.append(sink)
        return self

    def remove_sink(self, sink: Callable[[Span], None]) -> None:
        """Detach a sink added with :meth:`add_sink` (no-op when
        absent). Scoped consumers of the PROCESS tracer — simlab's
        per-run controller-span collector — must detach on teardown or
        every past run's sink keeps firing."""
        try:
            self._sinks.remove(sink)
        except ValueError:
            pass

    def current_span(self) -> Optional[Span]:
        """The innermost open span on THIS thread (None at top level).
        Capture it before submitting work to another thread and hand it
        to :meth:`adopt` there — cross-thread span parenting."""
        stack = self._stack()
        return stack[-1] if stack else None

    @contextmanager
    def adopt(self, parent: Optional[Span]) -> Iterator[None]:
        """Make ``parent`` (captured via :meth:`current_span` on another
        thread) the current span for this thread while the context is
        active: spans opened inside nest under it — same trace id,
        ``parent_id=parent.span_id`` — exactly as if they ran on the
        submitting thread. The parent span object is only *read* here
        (its ids), so adopting a still-open span owned by another thread
        is safe. No-op when ``parent`` is None (untraced caller)."""
        if parent is None:
            yield
            return
        stack = self._stack()
        stack.append(parent)
        active = _active_stack()
        active.append(parent)
        try:
            yield
        finally:
            stack.pop()
            active.pop()

    @contextmanager
    def adopt_remote(
        self, context: "Optional[RemoteContext | str]"
    ) -> Iterator[None]:
        """Adopt a CROSS-PROCESS parent: ``context`` is a
        :class:`RemoteContext` or a raw traceparent annotation value.
        Spans opened inside carry the remote trace id and parent the
        remote span id — the agent's reconcile tree continues the
        controller's desired-write trace. No-op (a local root as
        before) on None or a garbled value."""
        if isinstance(context, str):
            context = parse_traceparent(context)
        if not isinstance(context, RemoteContext):
            # None, or any non-context garbage off a node annotation:
            # degrade to a local root, never throw
            yield
            return
        with self.adopt(context):  # type: ignore[arg-type]
            yield

    # --------------------------------------------------------------- spans
    @contextmanager
    def span(self, name: str, **attrs: object) -> Iterator[Span]:
        """Time a phase. Exceptions mark the span failed and propagate."""
        stack = self._stack()
        parent = stack[-1] if stack else None
        sid = self._next_id()
        s = Span(
            name,
            trace_id=parent.trace_id if parent else sid,
            span_id=sid,
            parent_id=parent.span_id if parent else None,
            attrs=attrs,
        )
        t0 = time.monotonic()
        stack.append(s)
        active = _active_stack()
        active.append(s)
        try:
            yield s
        except BaseException as e:
            s.status = "error"
            s.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            s.dur_s = time.monotonic() - t0
            stack.pop()
            active.pop()
            self._record(s)

    def _record(self, s: Span) -> None:
        with self._lock:
            self._ring.append(s)
        for sink in self._sinks:
            try:
                sink(s)
            except Exception:  # a broken sink must never break a reconcile
                log.exception("trace sink failed")

    # ------------------------------------------------------------- reading
    def recent(self, limit: int = 256) -> List[dict]:
        """Most recent completed spans, oldest first."""
        with self._lock:  # snapshot: reconcile threads append concurrently
            items = list(self._ring)
        return [s.to_dict() for s in items[-limit:]]

    def traces(self, limit: int = 16) -> List[List[dict]]:
        """Recent spans grouped by trace id, oldest trace first."""
        with self._lock:
            items = list(self._ring)
        by_trace: Dict[str, List[dict]] = {}
        for s in items:
            by_trace.setdefault(s.trace_id, []).append(s.to_dict())
        return list(by_trace.values())[-limit:]


def _jsonl_cap_from_env() -> int:
    """``TPU_CC_TRACE_JSONL_MAX_MB`` -> byte cap (0 = unbounded; a
    typo degrades to unbounded — the historical behavior — rather
    than crashing an agent at startup)."""
    try:
        mb = float(os.environ.get("TPU_CC_TRACE_JSONL_MAX_MB", "") or 0)
    except ValueError:
        return 0
    return int(mb * 1024 * 1024) if mb > 0 else 0


class JsonlSink:
    """Append one JSON line per completed span to a file — the structured
    successor of the bash engine's ``set -x`` log. Enable with
    ``CC_TRACE_FILE=/var/log/tpu-cc-trace.jsonl``.

    Size-capped (``TPU_CC_TRACE_JSONL_MAX_MB``, or ``max_bytes``): when
    appending a span would push the file past the cap, the file rotates
    to ``<path>.1`` (replacing the previous rotation) and the span
    starts the fresh file — a long-running agent holds at most ~2x the
    cap on disk instead of filling it. Every span is still EXACTLY one
    complete line in exactly one of the two files: the size check and
    the write happen under one lock, and a line is never split across
    the rotation boundary."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = path
        self.max_bytes = (
            _jsonl_cap_from_env() if max_bytes is None else max_bytes
        )
        self.rotations = 0
        self._lock = threading.Lock()
        self._size: Optional[int] = None  # lazily stat'ed

    def _current_size(self) -> int:
        if self._size is None:
            try:
                self._size = os.path.getsize(self.path)
            except OSError:
                self._size = 0
        return self._size

    def __call__(self, span: Span) -> None:
        line = json.dumps(span.to_dict(), sort_keys=True) + "\n"
        data = len(line.encode("utf-8"))
        with self._lock:
            if (self.max_bytes
                    and self._current_size() + data > self.max_bytes
                    and self._current_size() > 0):
                try:
                    os.replace(self.path, self.path + ".1")
                    self.rotations += 1
                    # reset ONLY on success: a failed rotation leaves
                    # the full file in place, and believing it empty
                    # would let it grow by max_bytes per failed attempt
                    self._size = 0
                except OSError:
                    log.warning("trace jsonl rotation failed",
                                exc_info=True)
            with open(self.path, "a") as f:
                f.write(line)
            self._size = self._current_size() + data


_default = Tracer()


def get_tracer() -> Tracer:
    return _default


def set_tracer(tracer: Optional[Tracer]) -> None:
    """Swap the process-wide tracer (tests use this for isolation)."""
    global _default
    _default = tracer or Tracer()
