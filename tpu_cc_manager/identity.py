"""Platform identity for the evidence chain (VERDICT r3 missing #1).

The reference's security claim bottoms out in hardware: the flip
programs device registers and the device itself enforces the mode
(reference main.py:282-296 resets the GPU and re-queries it;
scripts/cc-manager.sh drives the same path). On TPU the attestation
mode is host-side durable state, so round 2 introduced the signed
evidence document — but its strongest link was an HMAC with a
POOL-SHARED key: any party holding the key (or root on any node of the
pool) could mint evidence for any other node.

This module adds the missing binding to a *platform* identity the pool
key cannot forge:

- On GCE/GKE, every node's metadata server mints **instance identity
  tokens** — RS256 JWTs signed by Google, carrying the instance name
  (which IS the GKE node name) and a caller-chosen audience. Only code
  running on that instance can obtain them; a stolen pool HMAC key on
  node A cannot produce node B's token.
- The agent attaches a fresh token to every evidence document
  (``doc["identity"]``); the document digest covers it, so the token
  and the device attestation are bound together.
- Verifiers (fleet audit, rollout judge) check the token's node
  binding and audience. A document signed with the stolen pool key but
  LACKING the node's identity token is flagged (``identity_missing``);
  a token minted for a different node is ``identity_mismatch``.

Providers:

- ``GceIdentity`` — fetches from the metadata server (host overridable
  for tests; 169.254.169.254 semantics). Full RS256 *signature*
  verification requires Google's JWKS, which an offline verifier may
  not reach — token claims (node binding, audience, expiry) are always
  checked, and the signature verdict degrades to ``unverifiable``
  without JWKS, exactly like the evidence HMAC degrades to ``no_key``.
- ``FakePlatformIdentity`` — HS256 with a test key, for tests and the
  smoke; with the key the signature IS verified, so the full
  forged-evidence drill runs hermetically.

Env knobs (documented in config.py):

- ``TPU_CC_IDENTITY``: ``auto`` (default: probe the metadata server
  once, cache the outcome), ``gce``, ``fake``, or ``none``.
- ``TPU_CC_IDENTITY_KEY[_FILE]``: HS256 key for the fake provider.
- ``TPU_CC_IDENTITY_AUDIENCE``: token audience (default
  ``tpu-cc-manager``) — pins tokens to this framework so an identity
  token minted for some other service cannot be replayed here.
- ``TPU_CC_REQUIRE_IDENTITY``: verifiers treat missing identity as a
  problem even on an all-missing pool (otherwise missing is only
  flagged on MIXED pools, where uniformity is the tell).
"""

from __future__ import annotations

import base64
import hashlib
import hmac as hmac_mod
import json
import logging
import os
import time
from typing import Optional, Tuple

log = logging.getLogger("tpu-cc-manager.identity")

DEFAULT_AUDIENCE = "tpu-cc-manager"

#: fraction of a token's lifetime remaining at which evidence should
#: be REPUBLISHED with a fresh token (agent idle tick, native-path
#: `evidence --sync`). Deliberately INSIDE _TokenCaching.refresh_margin
#: (0.25): by the time a republish is due, the provider cache already
#: refuses to serve the old token, so the rebuild fetches fresh instead
#: of re-serving and looping.
REPUBLISH_MARGIN = 0.2

#: metadata-server path serving instance identity tokens (GCE contract)
GCE_IDENTITY_PATH = (
    "/computeMetadata/v1/instance/service-accounts/default/identity"
)


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def identity_audience() -> str:
    return os.environ.get("TPU_CC_IDENTITY_AUDIENCE", DEFAULT_AUDIENCE)


def identity_key() -> Optional[bytes]:
    """HS256 key for the fake provider: TPU_CC_IDENTITY_KEY inline or
    TPU_CC_IDENTITY_KEY_FILE path. Missing file is silent (same
    optional-Secret posture as the evidence key)."""
    inline = os.environ.get("TPU_CC_IDENTITY_KEY", "")
    if inline:
        return inline.encode()
    path = os.environ.get("TPU_CC_IDENTITY_KEY_FILE", "")
    if path:
        try:
            with open(path, "rb") as f:
                return f.read().strip() or None
        except OSError:
            return None
    return None


# ------------------------------------------------------------- minting
def mint_fake_token(node_name: str, key: bytes, *,
                    audience: Optional[str] = None,
                    now: Optional[float] = None,
                    ttl_s: float = 3600.0) -> str:
    """HS256 JWT shaped like a GCE full-format instance identity token
    (claims nest under google.compute_engine the way the metadata
    server emits them), so verifiers exercise the same claim paths for
    fake and real tokens."""
    now = time.time() if now is None else now
    header = {"alg": "HS256", "typ": "JWT", "kid": "tpu-cc-fake"}
    payload = {
        "iss": "fake-metadata",
        "aud": audience or identity_audience(),
        "iat": int(now),
        "exp": int(now + ttl_s),
        "google": {"compute_engine": {"instance_name": node_name}},
    }
    signing_input = (
        _b64url(json.dumps(header, sort_keys=True).encode()) + "." +
        _b64url(json.dumps(payload, sort_keys=True).encode())
    )
    sig = hmac_mod.new(key, signing_input.encode(), hashlib.sha256).digest()
    return signing_input + "." + _b64url(sig)


class _TokenCaching:
    """Per-provider token cache. The reconcile path must not block on
    the metadata server (the evidence build is synchronous by design —
    agent.py builds it inline so device state isn't torn): steady-state
    flips hit the cache, and the agent's idle tick refreshes evidence —
    and with it the token — before expiry, so fetches happen off the
    hot path. ``refresh_margin`` is the fraction of remaining lifetime
    at which a cached token stops being served."""

    refresh_margin = 0.25

    def __init__(self):
        self._cache: dict = {}

    def cached_token(self, node_name: str,
                     audience: Optional[str] = None) -> str:
        aud = audience or identity_audience()
        now = time.time()
        hit = self._cache.get((node_name, aud))
        if hit is not None:
            tok, iat, exp = hit
            # opaque tokens (exp unknown) are never considered fresh —
            # they refetch every call rather than silently aging out
            if exp is not None and now < exp - self.refresh_margin * max(
                    exp - iat, 0):
                return tok
        try:
            tok = self.token(node_name, audience=aud)
        except Exception:
            # a fetch blip inside the refresh margin must not strip
            # identity: the cached token is still VALID (not expired),
            # just aging — serve it and let a later call refresh
            if hit is not None:
                tok, _iat, exp = hit
                if exp is not None and now < exp:
                    log.warning(
                        "identity token refresh failed; serving the "
                        "still-valid cached token", exc_info=True,
                    )
                    return tok
            raise
        iat, exp = now, None
        try:
            _, payload = token_claims(tok)
            if isinstance(payload.get("exp"), (int, float)):
                exp = float(payload["exp"])
            if isinstance(payload.get("iat"), (int, float)):
                iat = float(payload["iat"])
        except Exception:  # ccaudit: allow-swallow(opaque token is still servable; decode only feeds the expiry cache)
            pass
        self._cache[(node_name, aud)] = (tok, iat, exp)
        return tok


class FakePlatformIdentity(_TokenCaching):
    """Test/smoke provider: mints HS256 tokens with a shared key. The
    key plays the role of Google's signing key — hold it and you can
    mint identities, which is exactly the boundary the tests probe."""

    provider = "fake"

    def __init__(self, key: Optional[bytes] = None):
        super().__init__()
        #: explicit override; None = resolve the env key at token time,
        #: so a process-cached provider follows key-posture changes
        self._key = key

    def token(self, node_name: str,
              audience: Optional[str] = None) -> str:
        key = self._key if self._key is not None else identity_key()
        if not key:
            raise RuntimeError(
                "fake identity provider needs TPU_CC_IDENTITY_KEY[_FILE]"
            )
        return mint_fake_token(node_name, key, audience=audience)


class GceIdentity(_TokenCaching):
    """Fetches instance identity tokens from the GCE metadata server.
    ``node_name`` is ignored at mint time — the metadata server only
    ever speaks for its own instance, which is the entire point."""

    provider = "gce"

    def __init__(self, metadata_host: Optional[str] = None,
                 timeout_s: float = 1.0):
        super().__init__()
        self.metadata_host = metadata_host or os.environ.get(
            "TPU_CC_METADATA_HOST", "metadata.google.internal"
        )
        self.timeout_s = timeout_s

    def token(self, node_name: str,
              audience: Optional[str] = None) -> str:
        import urllib.parse
        import urllib.request

        aud = urllib.parse.quote(audience or identity_audience(),
                                 safe="")
        url = (
            f"http://{self.metadata_host}{GCE_IDENTITY_PATH}"
            f"?audience={aud}&format=full"
        )
        req = urllib.request.Request(
            url, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return resp.read().decode().strip()

    def probe(self) -> bool:
        """Cheap reachability check (instance id, not a token mint) for
        auto-detection — probing must not burn a full identity-token
        round trip just to throw the token away."""
        import urllib.request

        url = f"http://{self.metadata_host}/computeMetadata/v1/instance/id"
        req = urllib.request.Request(
            url, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s):
            return True


# ------------------------------------------------------- provider pick
#: cached auto-detection outcome: None = not probed yet; False = probed
#: and absent; provider otherwise. A hit is cached for the process
#: lifetime (the provider instance also holds the token cache); a MISS
#: is re-probed after _AUTO_RETRY_S — a metadata-server blip at agent
#: boot must not permanently strip identity from this node's evidence
_auto_cache: Optional[object] = None
_auto_probed_at: float = 0.0
_AUTO_RETRY_S = 300.0

#: explicit-mode provider singletons, so the token cache survives
#: across build_evidence calls
_explicit_cache: dict = {}


def get_identity_provider(refresh: bool = False):
    """Resolve the node's identity provider from TPU_CC_IDENTITY.
    ``auto`` probes the metadata server (negative outcome retried every
    ~5 min); explicit ``gce``/``fake`` trust the operator and skip
    probing. Returned instances are process-cached so their token
    caches persist."""
    global _auto_cache, _auto_probed_at
    mode = os.environ.get("TPU_CC_IDENTITY", "auto").lower()
    if mode in ("none", "off", "false", ""):
        return None
    if mode == "fake":
        if refresh or "fake" not in _explicit_cache:
            _explicit_cache["fake"] = FakePlatformIdentity()
        return _explicit_cache["fake"]
    if mode == "gce":
        if refresh or "gce" not in _explicit_cache:
            _explicit_cache["gce"] = GceIdentity()
        return _explicit_cache["gce"]
    now = time.monotonic()
    if refresh or (
            _auto_cache is False and now - _auto_probed_at > _AUTO_RETRY_S):
        _auto_cache = None
    if _auto_cache is None:
        _auto_probed_at = now
        prov = GceIdentity(timeout_s=0.5)
        try:
            prov.probe()
            _auto_cache = prov
        except Exception:
            log.debug("no ambient platform identity (metadata server "
                      "probe failed)", exc_info=True)
            _auto_cache = False
    return _auto_cache or None


# ------------------------------------------------------------- JWKS
#: SHA-256 DigestInfo prefix for EMSA-PKCS1-v1_5 (RFC 8017 §9.2)
_SHA256_DIGEST_INFO = bytes.fromhex(
    "3031300d060960864801650304020105000420"
)

#: cached parsed JWKS: path -> {kid: (n, e)}; mtime-checked so a
#: rotated ConfigMap mount is picked up without a restart
_jwks_cache: dict = {}


def load_jwks(path: str) -> dict:
    """Parse a JWKS document (the shape of Google's
    https://www.googleapis.com/oauth2/v3/certs, provisioned out-of-band
    — e.g. a ConfigMap refreshed by cluster tooling; this framework has
    no business dialing the public internet from a verifier) into
    {kid: (n, e)} RSA public numbers."""
    import json as _json

    with open(path) as f:
        doc = _json.load(f)
    keys = {}
    for key in doc.get("keys", []):
        if key.get("kty") != "RSA" or not key.get("kid"):
            continue
        try:
            n = int.from_bytes(_b64url_decode(key["n"]), "big")
            e = int.from_bytes(_b64url_decode(key["e"]), "big")
        except Exception:
            log.debug("skipping malformed JWKS key %r", key.get("kid"),
                      exc_info=True)
            continue
        keys[key["kid"]] = (n, e)
    return keys


def _jwks_for_env() -> Optional[dict]:
    """JWKS from TPU_CC_IDENTITY_JWKS_FILE, cached on (path, mtime).
    Missing file is silent — the optional-ConfigMap posture, same as
    the evidence key."""
    path = os.environ.get("TPU_CC_IDENTITY_JWKS_FILE", "")
    if not path:
        return None
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return None
    hit = _jwks_cache.get(path)
    if hit is not None and hit[0] == mtime:
        return hit[1]
    try:
        keys = load_jwks(path)
        if not keys:
            # present-but-unusable (EC-only keys, wrong document): the
            # operator believes offline verification is on — say once
            # per file version that the blind spot is still open
            log.warning(
                "JWKS file %s contains no usable RSA keys; RS256 "
                "tokens will degrade to 'unverifiable'", path,
            )
    except Exception:
        log.warning("cannot parse JWKS file %s", path, exc_info=True)
        keys = None
    # cache failures too (keyed on mtime): a broken file must not be
    # re-parsed and re-warned for every node of every fleet scan
    _jwks_cache[path] = (mtime, keys)
    return keys


def _rsa_pkcs1_sha256_verify(n: int, e: int, signing_input: bytes,
                             sig: bytes) -> bool:
    """RSASSA-PKCS1-v1_5 / SHA-256 verification from the public
    numbers, pure stdlib: s^e mod n must equal the EMSA-PKCS1-v1_5
    encoding of the hash. That encoding is fully deterministic, so
    verification is an exact compare — no parsing of attacker-shaped
    ASN.1 (the class of bug behind historic BER-laxity forgeries)."""
    k = (n.bit_length() + 7) // 8
    if len(sig) != k:
        return False
    m = pow(int.from_bytes(sig, "big"), e, n)
    em = m.to_bytes(k, "big")
    digest = hashlib.sha256(signing_input).digest()
    pad_len = k - 3 - len(_SHA256_DIGEST_INFO) - len(digest)
    if pad_len < 8:
        return False
    expected = (b"\x00\x01" + b"\xff" * pad_len + b"\x00"
                + _SHA256_DIGEST_INFO + digest)
    return hmac_mod.compare_digest(em, expected)


# ---------------------------------------------------------- verifying
def token_claims(token: str) -> Tuple[dict, dict]:
    """Parse (header, payload) WITHOUT verifying — callers must treat
    the claims as hostile until verify_token said otherwise."""
    parts = token.split(".")
    if len(parts) != 3:
        raise ValueError("not a three-part JWT")
    header = json.loads(_b64url_decode(parts[0]))
    payload = json.loads(_b64url_decode(parts[1]))
    if not isinstance(header, dict) or not isinstance(payload, dict):
        raise ValueError("JWT parts are not objects")
    return header, payload


def claimed_node(payload: dict) -> Optional[str]:
    """The node the token speaks for. GCE full-format tokens carry the
    instance name (== GKE node name) under google.compute_engine."""
    gce = (payload.get("google") or {}).get("compute_engine") or {}
    name = gce.get("instance_name")
    return name if isinstance(name, str) else None


def verify_token(token: str, *, node_name: str,
                 audience: Optional[str] = None,
                 key: Optional[bytes] = None,
                 jwks: Optional[dict] = None,
                 now: Optional[float] = None) -> Tuple[str, str]:
    """Judge an identity token. Returns (verdict, detail):

    - ``'ok'``: claims check out AND the signature verified (HS256
      with the configured key).
    - ``'unverifiable'``: claims check out but the signature cannot be
      judged here (RS256 without Google's JWKS, or HS256 without the
      key) — same tolerated-blind-spot posture as evidence 'no_key'.
    - ``'mismatch'``: the token speaks for a different node or a
      different audience — replay, the thing node binding exists for.
    - ``'expired'``: claims check out but the token is past its exp —
      STALE evidence (an idle node whose agent stopped refreshing),
      not forgery; verifiers class it with 'missing', not 'mismatch',
      so an idle fleet doesn't read as under attack.
    - ``'invalid'``: malformed or a bad signature.
    """
    audience = audience or identity_audience()
    if key is None:
        key = identity_key()
    now = time.time() if now is None else now
    try:
        header, payload = token_claims(token)
    except Exception as e:
        return "invalid", f"malformed token: {e}"
    # binding checks FIRST: an expired token for the wrong node is a
    # replay, and forensic findings outrank staleness
    if payload.get("aud") != audience:
        return "mismatch", (
            f"audience {payload.get('aud')!r}, expected {audience!r}"
        )
    bound = claimed_node(payload)
    if bound != node_name:
        return "mismatch", (
            f"token speaks for {bound!r}, not {node_name!r}"
        )
    exp = payload.get("exp")
    expired = isinstance(exp, (int, float)) and now > exp
    alg = header.get("alg")
    if alg == "HS256":
        if not key:
            return ("expired", "token expired") if expired else (
                "unverifiable", "HS256 token but no identity key here")
        signing_input, sig = token.rsplit(".", 1)
        expect = hmac_mod.new(
            key, signing_input.encode(), hashlib.sha256
        ).digest()
        if not hmac_mod.compare_digest(_b64url(expect), sig):
            return "invalid", "bad HS256 signature"
        return ("expired", "token expired") if expired else ("ok", "ok")
    if alg == "RS256":
        # Google-signed. With a provisioned JWKS
        # (TPU_CC_IDENTITY_JWKS_FILE, or the jwks param) the signature
        # is FULLY verified offline; without one, the claims are still
        # bound-checked above and the signature verdict degrades
        # honestly instead of rejecting every real GCE token
        if jwks is None:
            jwks = _jwks_for_env()
        if jwks:
            kid = header.get("kid")
            pub = jwks.get(kid)
            if pub is None:
                # NOT forgery: Google rotates its signing keys on the
                # order of days, and the provisioned ConfigMap can lag.
                # A stale verifier artifact must read as a blind spot
                # (same staleness-is-not-forgery posture as 'expired'),
                # never flag the whole fleet as under attack
                return ("expired", "token expired") if expired else (
                    "unverifiable",
                    f"no JWKS key for kid {kid!r} — JWKS ConfigMap "
                    "lagging a key rotation? refresh it",
                )
            signing_input, _, sig_b64 = token.rpartition(".")
            try:
                sig = _b64url_decode(sig_b64)
            except Exception as e:
                return "invalid", f"malformed RS256 signature: {e}"
            if not _rsa_pkcs1_sha256_verify(
                    pub[0], pub[1], signing_input.encode(), sig):
                return "invalid", "bad RS256 signature"
            return ("expired", "token expired") if expired else (
                "ok", "ok")
        return ("expired", "token expired") if expired else (
            "unverifiable", "RS256 signature needs Google JWKS")
    return "invalid", f"unsupported alg {alg!r}"


def judge_identity(doc: dict, node_name: str, *,
                   key: Optional[bytes] = None,
                   audience: Optional[str] = None,
                   now: Optional[float] = None) -> Tuple[str, str]:
    """Judge the ``identity`` field of an evidence document. Returns
    (verdict, detail) with verdicts ``ok | missing | expired |
    mismatch | invalid | unverifiable``. The evidence digest already
    covers the field, so a verifier that accepted the digest knows the
    identity it judges is the one the agent attached."""
    ident = doc.get("identity")
    if ident is None:
        return "missing", "no identity attached"
    if not isinstance(ident, dict) or not isinstance(
            ident.get("token"), str):
        return "invalid", "identity field malformed"
    return verify_token(
        ident["token"], node_name=node_name,
        audience=audience, key=key, now=now,
    )


def require_identity() -> bool:
    return os.environ.get(
        "TPU_CC_REQUIRE_IDENTITY", ""
    ).lower() in ("1", "true", "yes")
