"""The shared watch pump: ONE node-watch stream fanned out to N
replica mailboxes.

This is the piece that makes 256 live replicas affordable: instead of
256 per-node watch streams (each a held server thread + socket), one
stream over the whole fleet feeds every replica's last-value mailbox,
with the NodeWatcher's robustness contract kept intact — rv resume,
clean-timeout reconnect, error backoff, and full relist on 410 (the
reference main.py:675-687 behavior the watch_410 fault exercises).

Lag measurement: the runner stamps each desired-label patch
(:class:`LagStamps`); when the pump delivers that value for that node,
the stamp-to-delivery delta lands in the shared
``tpu_cc_watch_pump_lag_seconds`` histogram (obs.watch_pump_lag_histogram)
— the artifact's watch-pump lag distribution is measured at exactly the
point a per-node agent's mailbox would learn of the change.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.client import ApiException

log = logging.getLogger("tpu-cc-manager.simlab.pump")


class LagStamps:
    """One stamp per node: (desired value, monotonic patch time). The
    pump takes the stamp only when it delivers the SAME value — a
    coalesced-away intermediate flip never yields a bogus sample."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stamps: Dict[str, tuple] = {}

    def record(self, node: str, value: str, t: float) -> None:
        with self._lock:
            self._stamps[node] = (value, t)

    def take(self, node: str, value) -> Optional[float]:
        with self._lock:
            hit = self._stamps.get(node)
            if hit is None or hit[0] != value:
                return None
            del self._stamps[node]
            return hit[1]


class WatchPump:
    def __init__(
        self,
        kube,
        replicas: Dict[str, object],
        pool,
        stamps: LagStamps,
        lag_hist,
        *,
        watch_timeout_s: float = 10.0,
        backoff_s: float = 0.2,
    ):
        self.kube = kube
        self.replicas = replicas
        self.pool = pool
        self.stamps = stamps
        self.lag_hist = lag_hist
        self.watch_timeout_s = watch_timeout_s
        self.backoff_s = backoff_s
        self._rv: Optional[str] = None
        #: last desired value delivered downstream per node (the
        #: NodeWatcher._last_value dedup, fleet-wide)
        self._last: Dict[str, Optional[str]] = {}
        #: cc.trace annotation seen at each node's last desired CHANGE
        #: (the NodeWatcher freshness rule, fleet-wide): a new desired
        #: write only carries a trace if its writer stamped a FRESH
        #: context — an unstamped write must not inherit a finished
        #: write's annotation
        self._last_ctx: Dict[str, Optional[str]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters (monotonic; read for the artifact)
        self.events_total = 0       # watch events examined
        self.delivered_total = 0    # desired-mode changes fanned out
        self.echo_filtered_total = 0  # events with no desired change
        self.relists_total = 0
        self.errors_total = 0
        self.gone_410_total = 0
        self.lag_samples: List[float] = []
        self._lag_lock = threading.Lock()

    # ------------------------------------------------------------ plumbing
    def _observe_lag(self, node: str, value,
                     trace_id: Optional[str] = None) -> Optional[float]:
        t = self.stamps.take(node, value)
        if t is None:
            return None
        lag = time.monotonic() - t
        # the desired write's trace id exemplifies the lag bucket
        # (ISSUE 15): a slow pump bucket names a concrete fleet trace
        self.lag_hist.observe(lag, trace_id=trace_id)
        with self._lag_lock:
            self.lag_samples.append(lag)
        return lag

    def _deliver(self, node: str, value, trace: Optional[str] = None) -> None:
        if value == self._last.get(node):
            self.echo_filtered_total += 1
            return
        self._last[node] = value
        fresh = trace if trace != self._last_ctx.get(node) else None
        # ccaudit: allow-race-lockset(_deliver runs only on the pump thread after start(); prime() writes happen-before — same single-writer contract as _last)
        self._last_ctx[node] = trace
        from tpu_cc_manager.trace import parse_traceparent

        ctx = parse_traceparent(fresh)
        lag = self._observe_lag(
            node, value,
            trace_id=ctx.trace_id if ctx is not None else None)
        if value is None:
            return  # label removed: nothing to reconcile (no default)
        self.delivered_total += 1
        # the desired-writer's cc.trace context and this delivery's
        # measured pump lag travel WITH the value: the replica adopts
        # the trace and stamps the lag as a span attribute, so the
        # fleet-wide lag distribution also lands on the right spans.
        # Only a FRESHLY-stamped context rides (see _last_ctx)
        self.pool.submit(node, value, trace=fresh, lag=lag)

    def prime(self) -> None:
        """Initial LIST: seed per-node last values + the resume rv
        WITHOUT delivering (the runner submits the initial mode itself,
        so startup is one deliberate storm, not a list echo)."""
        nodes = self.kube.list_nodes()
        rv = 0
        for n in nodes:
            name = n["metadata"]["name"]
            if name in self.replicas:
                # ccaudit: allow-race-lockset(prime() runs before start() spawns the pump thread — happens-before, never concurrent with _deliver)
                self._last[name] = (n["metadata"].get("labels") or {}).get(
                    L.CC_MODE_LABEL
                )
                # seed the freshness baseline too: an annotation already
                # on the node at prime must not look freshly stamped
                # when the first unstamped desired change arrives
                # ccaudit: allow-race-lockset(prime() runs before start() — same happens-before as _last above)
                self._last_ctx[name] = (
                    n["metadata"].get("annotations") or {}
                ).get(L.CC_TRACE_ANNOTATION)
            rv = max(rv, int(n["metadata"].get("resourceVersion") or 0))
        # ccaudit: allow-race-lockset(prime() runs before start() — same happens-before as _last above)
        self._rv = str(rv) if rv else None

    def _relist(self) -> None:
        """Full resynchronization after 410 (or to recover from a list
        storm): compare-and-deliver, like the watcher's re-list path."""
        while not self._stop.is_set():
            try:
                nodes = self.kube.list_nodes()
                break
            except ApiException as e:
                # a 429/500 storm mid-relist: keep trying — the pump
                # wedged on a failed resync would strand the fleet
                self.errors_total += 1
                log.warning("relist failed (%s); retrying", e)
                self._stop.wait(self.backoff_s)
        else:
            return
        self.relists_total += 1
        rv = int(self._rv or 0)
        for n in nodes:
            name = n["metadata"]["name"]
            rv = max(rv, int(n["metadata"].get("resourceVersion") or 0))
            if name in self.replicas:
                self._deliver(
                    name,
                    (n["metadata"].get("labels") or {}).get(
                        L.CC_MODE_LABEL),
                    trace=(n["metadata"].get("annotations") or {}).get(
                        L.CC_TRACE_ANNOTATION),
                )
        self._rv = str(rv) if rv else None

    # ---------------------------------------------------------- main loop
    def run(self) -> None:
        while not self._stop.is_set():
            try:
                for etype, obj in self.kube.watch_nodes(
                    resource_version=self._rv,
                    # floor at 1: scenarios may say 0.5, and a
                    # truncated-to-0 window would busy-loop reconnects
                    # against the server under test
                    timeout_s=max(1, int(self.watch_timeout_s)),
                ):
                    meta = obj.get("metadata", {})
                    rv = meta.get("resourceVersion")
                    if rv is not None:
                        self._rv = rv
                    if etype == "BOOKMARK":
                        continue
                    self.events_total += 1
                    if etype == "DELETED":
                        continue
                    name = meta.get("name")
                    if name not in self.replicas:
                        continue
                    self._deliver(
                        name,
                        (meta.get("labels") or {}).get(L.CC_MODE_LABEL),
                        trace=(meta.get("annotations") or {}).get(
                            L.CC_TRACE_ANNOTATION),
                    )
                    if self._stop.is_set():
                        return
                # clean server-side timeout: reconnect immediately
            except ApiException as e:
                self.errors_total += 1
                if e.status == 410:
                    self.gone_410_total += 1
                    log.warning("watch history expired (410); relisting")
                    self._relist()
                    continue
                log.warning("watch error: %s; reconnecting in %.1fs",
                            e, self.backoff_s)
                self._stop.wait(self.backoff_s)
            except Exception:
                self.errors_total += 1
                log.exception("unexpected pump error")
                self._stop.wait(self.backoff_s)

    # --------------------------------------------------------- lifecycle
    def start(self) -> "WatchPump":
        self._thread = threading.Thread(
            target=self.run, name="simlab-pump", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def stats(self) -> dict:
        from tpu_cc_manager.simlab.report import percentile

        with self._lag_lock:
            samples = list(self.lag_samples)
        return {
            "events": self.events_total,
            "delivered": self.delivered_total,
            "echo_filtered": self.echo_filtered_total,
            "relists": self.relists_total,
            "watch_errors": self.errors_total,
            "watch_410": self.gone_410_total,
            "lag_samples": len(samples),
            "lag_p50_s": percentile(samples, 0.50),
            "lag_p95_s": percentile(samples, 0.95),
            "lag_max_s": round(max(samples), 5) if samples else None,
        }
