"""FederationLab — one scenario, N regions, N live API servers.

The multi-region counterpart of :class:`~tpu_cc_manager.simlab.runner.
SimLab` (ISSUE 16): a schema-2 scenario with ``regions`` gets one
FULL per-region cell — its own :class:`FakeApiServer`, its own live
replica fleet (worker pool + watch pump), its own attestation trust
domain — federated by ONE :class:`~tpu_cc_manager.federation.
FederationManager` whose region-affine ring, posture windows, and
evacuation flow are exactly what production runs.

What the lab measures beyond SimLab:

- ``region_evac_convergence_s`` — region_evacuate injection → the
  fleet stable again (evacuated region fully cordoned AND every other
  region converged after absorbing); the bench axis ISSUE 16 gates.
- the cross-region ``e2e_convergence_p99_s`` — stitched over flight-
  recorder trace ids from every region's desired_write spans (the
  federation controller stamps them on the process tracer) joined to
  every region's replica reconcile spans.
- per-region fault surfaces: ``region_partition`` / ``region_blackout``
  (FakeKube's blackout gate severs that region's API server),
  ``region_latency_skew`` (response_delay_s), ``region_evacuate``,
  and region-scoped ``root_revoked`` (that region's trust domain only —
  the region_attestation_latch invariant pins the non-spill).

The lab exposes the same judgment surface SimLab does (``replicas``,
``final_fleet_reports()``, ``scenario``) so the invariants oracle
(:mod:`~tpu_cc_manager.simlab.invariants`) runs unchanged; the
store-scoped checks see ``server is None`` and skip, and the
federation-specific contract is judged from the artifact's
``metrics.federation`` block.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from tpu_cc_manager import labels as L
from tpu_cc_manager.device.fake import fake_backend
from tpu_cc_manager.federation import (
    FederationManager, FleetPosture, RegionSpec, RegionTrustDomain,
)
from tpu_cc_manager.flightrec import FlightRecorder, stitch_by_trace
from tpu_cc_manager.k8s.apiserver import FakeApiServer
from tpu_cc_manager.k8s.client import HttpKubeClient, KubeConfig
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.obs import (
    Metrics, kube_throttle_wait_histogram, watch_pump_lag_histogram,
)
from tpu_cc_manager.simlab.pump import LagStamps, WatchPump
from tpu_cc_manager.simlab.replica import (
    _EMPTY as _REPLICA_EMPTY, ReplicaShell, WorkerPool,
)
from tpu_cc_manager.simlab.report import build_artifact, percentile
from tpu_cc_manager.simlab.runner import POOL_LABEL, _env_int
from tpu_cc_manager.simlab.scenario import Scenario, ScenarioError
from tpu_cc_manager.trace import Tracer, get_tracer

log = logging.getLogger("tpu-cc-manager.simlab.federation")

#: region fault kinds the lab executes (scenario.py validates them)
_HEAL_DEFAULT_S = 5.0


class _RegionCell:
    """One region's live assembly: API server, node fleet, replicas,
    worker pool, watch pump, and (when the scenario runs attestation)
    per-node TPMs keyed to the region's OWN trust domain — explicit
    keys, never the process env, because two regions must be able to
    trust different roots in one process."""

    def __init__(self, lab: "FederationLab", region, index: int) -> None:
        sc = lab.scenario
        self.name = region.name
        self.spec = region
        self.server = FakeApiServer().start()
        self.store = self.server.store
        self.pools = [f"{region.name}-p{j}" for j in range(region.pools)]
        self.node_names = [
            f"{region.name}-{i:04d}" for i in range(region.nodes)
        ]
        for i, name in enumerate(self.node_names):
            self.store.add_node(make_node(name, labels={
                L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
                POOL_LABEL: self.pools[i % region.pools],
                L.CC_MODE_LABEL: sc.initial_mode,
            }))
        self.trust_domain: Optional[RegionTrustDomain] = None
        self._tpms: Dict[str, object] = {}
        if sc.attestation:
            from tpu_cc_manager.attest import FakeTpm

            key = f"simlab-fed-{region.name}-key-0".encode()
            self.trust_domain = RegionTrustDomain(region.name, (key,))
            for name in self.node_names:
                self._tpms[name] = FakeTpm(
                    state_dir=os.path.join(lab.tpm_dir, name), key=key,
                )
        self.data_kube = self._client(qps=sc.qps)
        self.data_kube.add_throttle_observer(lab._observe_throttle)
        self.replicas: Dict[str, ReplicaShell] = {
            name: ReplicaShell(
                name, self.data_kube,
                fake_backend(n_chips=sc.chips_per_node),
                lab.tracer, evidence=sc.evidence,
                metrics=Metrics(),
                attestor=self._tpms.get(name),
            )
            for name in self.node_names
        }
        self.pool = WorkerPool(self.replicas, lab.region_workers).start()
        self.pump = WatchPump(
            self._client(qps=0), self.replicas, self.pool,
            lab.stamps, lab.lag_hist,
            watch_timeout_s=sc.watch_timeout_s,
        )
        self.pump.prime()
        self.pump.start()

    def _client(self, qps: float = 0.0) -> HttpKubeClient:
        return HttpKubeClient(
            KubeConfig("127.0.0.1", self.server.port, use_tls=False),
            qps=qps,
        )

    def stop(self) -> None:
        self.pump.stop()
        self.pool.stop()
        self.server.stop()


class FederationLab:
    """Run one schema-2 ``regions`` scenario end to end."""

    def __init__(self, scenario: Scenario):
        if not scenario.regions:
            raise ScenarioError(
                f"scenario {scenario.name!r} has no regions — use SimLab"
            )
        self.scenario = scenario
        self.workers = _env_int("TPU_CC_SIMLAB_WORKERS",
                                scenario.workers)
        # each region runs its own worker pool against its own server;
        # splitting the scenario's budget keeps the total thread count
        # (the 1-core sandbox constraint) what the scenario asked for
        self.region_workers = max(
            2, self.workers // len(scenario.regions))
        #: SimLab-compatible judgment surface for the invariants oracle:
        #: no single store (checks that need one skip via None)
        self.server = None
        self.injector = None
        self.attest_lab = None
        self.shard_manager = None
        self.cells: Dict[str, _RegionCell] = {}
        self.replicas: Dict[str, ReplicaShell] = {}
        self.fed: Optional[FederationManager] = None
        self.stamps = LagStamps()
        self.lag_hist = watch_pump_lag_histogram()
        self.throttle_hist = kube_throttle_wait_histogram()
        self._throttle_samples: List[float] = []
        self._throttle_lock = threading.Lock()
        self._phase_durations: Dict[str, List[float]] = {}
        self._phase_lock = threading.Lock()
        self.tracer = Tracer()
        self.tracer.add_sink(self._phase_sink)
        self._tmp = tempfile.TemporaryDirectory(prefix="simlab-fed-tpm-")
        self.tpm_dir = self._tmp.name
        # the federation controller's desired_write spans land on the
        # PROCESS tracer (rollout/federation get_tracer()) — the same
        # filtered-sink capture SimLab uses for policy rollouts
        self.ctrl_rec = FlightRecorder(
            name="controller", span_ring=256, event_ring=8, sample_ring=8,
        )

        def _ctrl_sink(span) -> None:
            if span.name == "desired_write":
                self.ctrl_rec.observe_span(span)

        self._ctrl_sink = _ctrl_sink
        #: heal timers for duration-bounded region faults; settle fires
        #: any still pending so the judged fleet is the healed one
        self._heal_timers: List[threading.Timer] = []
        self._heal_lock = threading.Lock()
        #: monotonic stamp of the region_evacuate injection (the
        #: region_evac_convergence_s axis is this -> fleet stable)
        self._t_evac: Optional[float] = None
        self._conv_end_t: Optional[float] = None

    # ------------------------------------------------------------ plumbing
    def _phase_sink(self, span) -> None:
        with self._phase_lock:
            self._phase_durations.setdefault(span.name, []).append(
                span.dur_s
            )

    def _observe_throttle(self, waited: float) -> None:
        self.throttle_hist.observe(waited)
        if waited > 0:
            with self._throttle_lock:
                self._throttle_samples.append(waited)

    def _cell_of(self, region: str) -> _RegionCell:
        cell = self.cells.get(region)
        if cell is None:
            raise ScenarioError(f"unknown region {region!r}")
        return cell

    def _heal_later(self, delay_s: float, fn) -> None:
        t = threading.Timer(delay_s, fn)
        t.daemon = True
        with self._heal_lock:
            self._heal_timers.append(t)
        t.start()

    # -------------------------------------------------------------- setup
    def _build(self) -> None:
        sc = self.scenario
        for i, region in enumerate(sc.regions):
            cell = _RegionCell(self, region, i)
            self.cells[region.name] = cell
            self.replicas.update(cell.replicas)
        self.fed = FederationManager(
            [
                RegionSpec(
                    name=cell.name,
                    client_factory=cell._client,
                    pools=list(cell.pools),
                    trust_domain=cell.trust_domain,
                )
                for cell in self.cells.values()
            ],
            pool_label=POOL_LABEL,
            shards_per_region=max(1, sc.controllers.shards or 1),
            policy=False,
            fleet_interval_s=1.0,
        )
        self.fed.start()
        if not self.fed.wait_covered(timeout_s=30.0):
            log.warning("federation did not reach full coverage before "
                        "the timeline; continuing")

    # --------------------------------------------------- fleet plane taps
    def _region_fleet_controllers(self, region: str) -> List[object]:
        return [b.fleet
                for b in self.fed.managers[region].bundles()]

    def _region_armed(self, region: str) -> bool:
        return any(
            getattr(c, "attestation_ever_verified", False)
            for c in self._region_fleet_controllers(region)
        )

    def final_fleet_reports(self) -> List[dict]:
        out = []
        for region in sorted(self.cells):
            for c in self._region_fleet_controllers(region):
                if getattr(c, "last_report", None):
                    out.append(c.last_report)
        return out

    # ------------------------------------------------------------- actions
    def _act_set_mode(self, params: dict) -> dict:
        posture = FleetPosture(
            mode=params["mode"],
            windows=dict(params.get("windows") or {}),
            source="timeline",
        )
        self.fed.apply_posture(posture)
        return {"mode": posture.mode,
                "windows": dict(posture.windows),
                "regions": self.fed.regions}

    def _inject(self, kind: str, params: dict, rel_t: float) -> dict:
        entry: dict = {"fault": kind, "at_s": round(rel_t, 3)}
        entry.update({k: v for k, v in params.items()})
        if kind == "region_partition" or kind == "region_blackout":
            region = params["region"]
            cell = self._cell_of(region)
            duration = float(params.get("duration_s", _HEAL_DEFAULT_S))
            cell.store.blackout = True
            self.fed.set_partitioned(region, True)

            def _heal(cell=cell, region=region):
                cell.store.blackout = False
                self.fed.set_partitioned(region, False)
                log.info("region %s: %s healed", region, kind)

            self._heal_later(duration, _heal)
            entry["duration_s"] = duration
        elif kind == "region_latency_skew":
            region = params["region"]
            cell = self._cell_of(region)
            delay = float(params["delay_s"])
            duration = float(params.get("duration_s", _HEAL_DEFAULT_S))
            cell.store.response_delay_s = delay

            def _heal(cell=cell, region=region):
                cell.store.response_delay_s = 0.0
                log.info("region %s: latency skew healed", region)

            self._heal_later(duration, _heal)
            entry["duration_s"] = duration
        elif kind == "region_evacuate":
            region = params["region"]
            self._cell_of(region)
            if self._t_evac is None:
                self._t_evac = time.monotonic()
            entry.update(self.fed.evacuate(region))
        elif kind == "root_revoked":
            # region-scoped by default in a federation scenario: only
            # THAT region's trust domain drops. Without a region the
            # drill revokes every domain (the single-region analog).
            targets = ([params["region"]] if params.get("region")
                       else sorted(self.cells))
            armed: Dict[str, bool] = {}
            for region in targets:
                deadline = time.monotonic() + 30.0
                while (not self._region_armed(region)
                       and time.monotonic() < deadline):
                    time.sleep(0.1)
                armed[region] = self._region_armed(region)
                domain = self.cells[region].trust_domain
                if domain is None:
                    raise ScenarioError(
                        "root_revoked needs attestation: true")
                domain.revoke()
                log.warning("region %s: trust root revoked (armed=%s)",
                            region, armed[region])
            entry["regions_revoked"] = targets
            # same key the single-region oracle reads: was at least one
            # quote verified before the revocation latched?
            entry["armed_before_revoke"] = all(armed.values())
            entry["armed_by_region"] = armed
        else:
            # schema validation already scoped the timeline; anything
            # else here is a scenario the federation lab cannot drive
            raise ScenarioError(
                f"fault {kind!r} is not supported by the federation lab"
            )
        return entry

    # --------------------------------------------------------- convergence
    def _wait_converged(self, target: str, timeout_s: float,
                        initial: bool = False):
        """(elapsed_s or None, pending). Non-evacuated regions: every
        node's state label at ``target`` (out-of-band store peek, like
        SimLab — measurement must add no HTTP load). Evacuated regions:
        fully cordoned, judged via the federation's own informer-cache
        check (zero store reads by construction)."""
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        evacuated = set() if initial else set(
            self.fed.stats()["evacuated"])
        pending_nodes = {
            name
            for region, cell in self.cells.items()
            if region not in evacuated
            for name in cell.node_names
        }
        pending_cordons = set(evacuated)
        cell_of_node = {
            name: cell
            for cell in self.cells.values() for name in cell.node_names
        }
        while (pending_nodes or pending_cordons) and \
                time.monotonic() < deadline:
            # evacuation can land mid-wait: re-scope the judgment
            if not initial:
                now_evac = set(self.fed.stats()["evacuated"])
                for region in now_evac - evacuated:
                    evacuated.add(region)
                    pending_cordons.add(region)
                    pending_nodes -= set(
                        self.cells[region].node_names)
            pending_nodes = {
                n for n in pending_nodes
                if cell_of_node[n].store.peek_node_label(
                    n, L.CC_MODE_STATE_LABEL) != target
            }
            pending_cordons = {
                r for r in pending_cordons
                if not self.fed.region_cordoned(r)
            }
            if pending_nodes or pending_cordons:
                time.sleep(0.05)
        pending = sorted(pending_nodes) + sorted(
            f"region:{r}:cordon" for r in pending_cordons)
        if pending:
            return None, pending
        return time.monotonic() - t0, []

    # ----------------------------------------------------------------- run
    def run(self) -> dict:
        sc = self.scenario
        os.environ.setdefault("TPU_CC_IDENTITY", "none")
        os.environ.setdefault("TPU_CC_ATTESTATION", "none")
        log.info("federation lab: scenario %r — %d nodes over %d "
                 "regions (%s)", sc.name, sc.nodes, len(sc.regions),
                 ", ".join(f"{r.name}:{r.nodes}" for r in sc.regions))
        get_tracer().add_sink(self._ctrl_sink)
        notes = None
        faults: List[dict] = []
        try:
            self._build()

            # initial storm to initial_mode, outside the measurement
            for cell in self.cells.values():
                for name in cell.node_names:
                    cell.pool.submit(name, sc.initial_mode)
            initial_s, pending = self._wait_converged(
                sc.initial_mode, min(60.0, sc.converge.timeout_s),
                initial=True,
            )
            if initial_s is None:
                notes = (f"{len(pending)} replicas never initialized "
                         f"to {sc.initial_mode!r}")
                return self._finish(False, None, None, pending, faults,
                                    notes)

            # ---- the timeline (actions pre-sorted by `at`)
            t0 = time.monotonic()
            t_change: Optional[float] = None
            for action in sc.actions:
                delay = t0 + action.at - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                rel_t = time.monotonic() - t0
                if action.kind == "fault":
                    params = dict(action.params)
                    kind = params.pop("fault")
                    faults.append(self._inject(kind, params, rel_t))
                    continue
                if action.kind != "set_mode":
                    raise ScenarioError(
                        f"action {action.kind!r} is not supported by "
                        "the federation lab")
                entry = self._act_set_mode(action.params)
                entry.update({"at_s": round(rel_t, 3),
                              "action": action.kind})
                faults.append(entry)
                if (t_change is None
                        and action.params["mode"] == sc.converge.mode):
                    t_change = time.monotonic()

            conv_s, pending = self._wait_converged(
                sc.converge.mode, sc.converge.timeout_s
            )
            if conv_s is not None:
                self._conv_end_t = time.monotonic()
                if t_change is not None:
                    conv_s = self._conv_end_t - t_change
            ok = conv_s is not None
            if ok:
                self._settle()
            if not ok:
                notes = (f"{len(pending)} judgment(s) never reached "
                         f"{sc.converge.mode!r} within "
                         f"{sc.converge.timeout_s}s")
            return self._finish(ok, initial_s, conv_s, pending, faults,
                                notes)
        finally:
            self._teardown()

    def _settle(self) -> None:
        """Heal any still-pending region fault, drain straggler
        reconciles, flush deferred publications, then one final fleet
        scan per region so the artifact's audit (and the
        region_attestation_latch judgment) reflects the settled
        fleet."""
        with self._heal_lock:
            timers = list(self._heal_timers)
            self._heal_timers = []
        for t in timers:
            t.cancel()
            try:
                t.function()
            except Exception:
                log.warning("settle heal failed", exc_info=True)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            busy = any(
                r._queued or r._pending is not _REPLICA_EMPTY
                for r in self.replicas.values()
            )
            if not busy:
                break
            time.sleep(0.05)
        for r in self.replicas.values():
            r.batcher.flush()
        for region in sorted(self.cells):
            for c in self._region_fleet_controllers(region):
                try:
                    c.scan_once()
                except Exception:
                    log.warning("final fleet scan failed (region %s)",
                                region, exc_info=True)

    # ------------------------------------------------------ trace stitch
    def _stitch_traces(self) -> dict:
        """Stitch the CROSS-REGION causal story: every region's
        desired_write span (federation controller, process tracer) is
        joined by trace id to every region's replica reconcile spans —
        the e2e convergence distribution spans API servers, which no
        single region's recorder could produce."""
        recordings = [self.ctrl_rec.snapshot("run_end")]
        for r in self.replicas.values():
            recordings.append(r.recorder.snapshot("run_end"))
        stitched = stitch_by_trace(recordings)
        samples: List[float] = []
        cross = 0
        example: List[dict] = []
        for spans in stitched.values():
            recorders = {s.get("recorder") for s in spans
                         if s.get("recorder")}
            desired = [s for s in spans if s["name"] == "desired_write"]
            if len(recorders) > 1 and desired:
                cross += 1
                if len(spans) > len(example):
                    example = spans
            if not desired:
                continue
            t0 = min(s["start_ts"] for s in desired)
            ends: Dict[str, float] = {}
            for s in spans:
                if s["name"] != "reconcile":
                    continue
                node = ((s.get("attrs") or {}).get("node")
                        or s.get("recorder"))
                end = s["start_ts"] + s["dur_s"]
                if node and end > ends.get(node, 0.0):
                    ends[node] = end
            samples.extend(
                max(0.0, end - t0) for end in ends.values()
            )
        return {
            "traces": len(stitched),
            "cross_process_traces": cross,
            "e2e_samples": len(samples),
            "e2e_convergence_p50_s": percentile(samples, 0.50),
            "e2e_convergence_p99_s": percentile(samples, 0.99),
            "timeline_example": example[:12],
        }

    # ------------------------------------------------------------- finish
    def _federation_block(self, conv_ok: bool) -> dict:
        stats = self.fed.stats() if self.fed is not None else {}
        evacuated = set(stats.get("evacuated") or ())
        regions: Dict[str, dict] = {}
        for name, cell in sorted(self.cells.items()):
            regions[name] = {
                "nodes": len(cell.node_names),
                "pools": list(cell.pools),
                # the zero-cross-region-reads ledger: each region's
                # FakeKube counts ONLY its own traffic; a regression
                # reader can compare steady-state read rates per region
                "node_read_requests": cell.store.node_read_requests,
                "evacuated": name in evacuated,
            }
        block = {
            "regions": regions,
            "posture": stats.get("posture"),
            "evacuations": stats.get("evacuations") or [],
            "partitioned": stats.get("partitioned") or [],
            "attestation": (self.fed.attestation_summary()
                            if self.fed is not None else {}),
        }
        if self._t_evac is not None:
            if conv_ok and self._conv_end_t is not None:
                block["region_evac_convergence_s"] = round(
                    max(0.0, self._conv_end_t - self._t_evac), 4)
            else:
                # a failed evac drill leaves the axis ABSENT — bench.py
                # fails loudly on None rather than gating a lie
                log.error("region evacuation never stabilized; the "
                          "region_evac_convergence_s axis stays absent")
        return block

    def _finish(self, ok, initial_s, conv_s, pending, faults, notes):
        replica_stats = {"total": 0, "repairs": 0, "coalesced": 0}
        publish_stats = {"coalesced": 0, "folded": 0, "flushed": 0,
                         "retries": 0, "dropped": 0, "pending": 0}
        for r in self.replicas.values():
            replica_stats["total"] += r.reconciles
            replica_stats["repairs"] += r.repairs
            replica_stats["coalesced"] += r.coalesced
            for outcome, n in r.outcomes.items():
                replica_stats[outcome] = (
                    replica_stats.get(outcome, 0) + n
                )
            for k, v in r.batcher.stats().items():
                publish_stats[k] = publish_stats.get(k, 0) + v
        replica_stats["publish"] = publish_stats
        replica_stats["api_writes"] = {
            name: cell.store.node_write_stats()
            for name, cell in sorted(self.cells.items())
        }
        with self._throttle_lock:
            waits = list(self._throttle_samples)
        throttle = {
            "waits": sum(c.data_kube.throttle_waits
                         for c in self.cells.values()),
            "wait_s_total": round(
                sum(c.data_kube.throttle_wait_s_total
                    for c in self.cells.values()), 4),
            "wait_p50_s": percentile(waits, 0.50),
            "wait_max_s": round(max(waits), 5) if waits else None,
            "histogram": self.throttle_hist.snapshot(),
        }
        controllers = {
            "running": sum(
                len(self._region_fleet_controllers(r))
                for r in self.cells
            ) if self.fed is not None else 0,
            "federation": self.fed.stats() if self.fed is not None
            else None,
        }
        reports = self.final_fleet_reports()
        problems = [p for rep in reports
                    for p in (rep.get("problems") or [])]
        if problems:
            controllers["fleet_problems"] = [
                p if len(p) <= 160 else p[:160] + "..."
                for p in problems[:5]
            ]
            controllers["fleet_problem_count"] = len(problems)
        lifecycle = {"versions": {}}
        for r in self.replicas.values():
            lifecycle["versions"][r.version] = (
                lifecycle["versions"].get(r.version, 0) + 1
            )
        with self._phase_lock:
            phase_durations = {
                k: list(v) for k, v in self._phase_durations.items()
            }
        pump_stats = {
            name: cell.pump.stats()
            for name, cell in sorted(self.cells.items())
        }
        return build_artifact(
            self.scenario,
            ok=ok,
            initial_convergence_s=initial_s,
            convergence_s=conv_s,
            pending=pending,
            pump_stats=pump_stats,
            throttle=throttle,
            phase_durations=phase_durations,
            replica_stats=replica_stats,
            faults=faults,
            controllers=controllers,
            trace_stitch=self._stitch_traces(),
            lifecycle=lifecycle,
            kube_io={"core": "threaded", "regions": len(self.cells)},
            federation=self._federation_block(ok),
            notes=notes,
        )

    def _teardown(self) -> None:
        get_tracer().remove_sink(self._ctrl_sink)
        with self._heal_lock:
            timers = list(self._heal_timers)
            self._heal_timers = []
        for t in timers:
            t.cancel()
        # heal blackouts BEFORE stopping: a stopped server with the
        # gate still raised would hang client close paths on retries
        for cell in self.cells.values():
            cell.store.blackout = False
            cell.store.response_delay_s = 0.0
        if self.fed is not None:
            try:
                self.fed.stop()
            except Exception:
                log.warning("federation stop failed", exc_info=True)
        for cell in self.cells.values():
            try:
                cell.stop()
            except Exception:
                log.warning("region %s teardown failed", cell.name,
                            exc_info=True)
        self._tmp.cleanup()
