"""The convergence-and-invariants oracle (ISSUE 12).

One reusable judgment over a finished simlab run: did the fleet not
only converge, but converge WITHOUT violating the contracts the
reconciler protocol promises under any interleaving of faults? The
property-based generator (simlab.propgen) runs every episode through
this oracle; hand-written scenarios and CI smokes can use it too — the
checks are feature-conditional, so a scenario without shards simply
skips the shard invariants.

The catalog (stable ids — shrink targets and reports key on them):

- ``convergence``      — every node reached converge.mode in budget
- ``half_flipped``     — no node's chips disagree at quiescence
- ``fail_secure``      — no CONVERGED node still holds a device at
  FLIP_LOCK_PERMS (a failing flip keeps its device locked; a verified
  one must reopen it — both directions of device/gate.py's contract)
- ``writes_per_flip``  — the fleet's logical node-write mutations stay
  inside the coalescing budget (≤ 1 state + 1 evidence unit per flip,
  plus exactly-accounted controller/fault writes) — the invariant that
  catches silent un-batching back toward the historical ~5 writes/flip
- ``leader_uniqueness`` — no shard partition held by two live hosts
- ``forged_evidence``  — a planted node-root forgery is never accepted:
  judged ``mismatch``, bucketed by the final audit, and the victim's
  chips never moved to the forged claim
- ``attestation_outage`` — a revoked verifier root LATCHES the
  attestation_outage problem and the fleet never reads verified again
- ``attestation_rotation`` — after a key rotation every node's settled
  evidence re-verifies under the NEW primary alone (no mismatch tail)
- ``region_attestation_latch`` — a region-scoped root revocation
  (federation) latches attestation_outage in the revoked region ONLY:
  sibling regions keep verifying, and the revoked region never reads
  verified again
- ``policy_conflict``  — the rival overlapping policy is parked in
  phase Conflicted; the owner is healthy
- ``upgrade_completeness`` — every upgraded replica is alive and its
  node advertises the new code version at quiescence
- ``evacuation_restored`` — no node is left cordoned by an evacuation
- ``exposition_valid`` — the merged fleet exposition (shards) and the
  observatory aggregation stayed valid

Checks read the LIVE lab (replica backends, gate recordings, the
store) plus the artifact — the oracle must see device truth, not just
what the labels claim.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Tuple

from tpu_cc_manager import labels as L
from tpu_cc_manager.device.gate import FLIP_LOCK_PERMS
from tpu_cc_manager.modes import STATE_FAILED

log = logging.getLogger("tpu-cc-manager.simlab.invariants")

#: invariant id -> one-line contract (docs/simlab.md renders this)
INVARIANTS: Dict[str, str] = {
    "convergence": "every node reaches converge.mode within budget",
    "half_flipped": "no node's chips disagree on cc mode at quiescence",
    "fail_secure": "no converged node still holds a flip-locked device",
    "writes_per_flip": "node-write mutations stay in the coalescing "
                       "budget (~2 units per flip)",
    "leader_uniqueness": "no shard partition held by two live hosts",
    "forged_evidence": "a forged evidence document is never accepted "
                       "and never flips a chip",
    "attestation_outage": "a revoked verifier root latches the "
                          "attestation_outage problem",
    "attestation_rotation": "rotated-key evidence re-verifies under "
                            "the new primary alone",
    "region_attestation_latch": "a revoked region trust root latches "
                                "attestation_outage in THAT region "
                                "only — no spill, no spare",
    "policy_conflict": "the rival overlapping policy parks in phase "
                       "Conflicted; the owner stays healthy",
    "upgrade_completeness": "every upgraded replica is alive and "
                            "advertises its new version",
    "evacuation_restored": "no node is left cordoned by an evacuation",
    "exposition_valid": "merged fleet exposition / SLO aggregation "
                        "stayed valid",
}


@dataclasses.dataclass(frozen=True)
class Violation:
    """One broken invariant: a stable id, a human-readable detail, and
    the nodes involved (capped by the caller when rendering)."""

    invariant: str
    detail: str
    nodes: Tuple[str, ...] = ()

    def to_dict(self) -> dict:
        return {"invariant": self.invariant, "detail": self.detail,
                "nodes": list(self.nodes)[:16]}


def _fault_entries(artifact: dict, kind: str) -> List[dict]:
    return [f for f in artifact.get("faults") or []
            if f.get("fault") == kind]


# ------------------------------------------------------------- checks
def _check_convergence(lab, artifact, out: List[Violation]) -> None:
    if not artifact.get("ok"):
        out.append(Violation(
            "convergence",
            artifact.get("notes") or "scenario did not converge",
            tuple(artifact.get("pending_nodes") or ()),
        ))


def _check_half_flipped(lab, artifact, out: List[Violation]) -> None:
    for name, replica in sorted(lab.replicas.items()):
        modes = set()
        for chip in getattr(replica.backend, "chips", []):
            if not chip.is_cc_query_supported:
                continue
            try:
                modes.add(chip.query_cc_mode())
            except Exception:  # ccaudit: allow-swallow(an unqueryable chip is recorded as its own sentinel mode — disagreement, not silence)
                modes.add("<unqueryable>")
        if len(modes) > 1:
            out.append(Violation(
                "half_flipped",
                f"{name}: chips disagree on cc mode at quiescence: "
                f"{sorted(modes)}",
                (name,),
            ))


def _check_fail_secure(lab, artifact, out: List[Violation]) -> None:
    store = lab.server.store if lab.server is not None else None
    for name, replica in sorted(lab.replicas.items()):
        gate = getattr(replica, "gate", None)
        if gate is None or not hasattr(gate, "perms_snapshot"):
            continue
        locked = sorted(
            path for path, perms in gate.perms_snapshot().items()
            if perms == FLIP_LOCK_PERMS
        )
        if not locked:
            continue
        state = None
        if store is not None:
            try:
                state = store.peek_node_label(
                    name, L.CC_MODE_STATE_LABEL)
            except Exception:  # ccaudit: allow-swallow(post-run probe; an unreadable label reads as unknown and the check stays conservative)
                state = None
        # fail-secure is the point: a FAILED node keeping its device
        # locked is correct. A node whose label claims a successfully
        # applied mode while a device is still at FLIP_LOCK_PERMS has
        # handed workloads a gated chip — the contract break.
        if state is not None and state != STATE_FAILED:
            out.append(Violation(
                "fail_secure",
                f"{name}: state label claims {state!r} but device(s) "
                f"{locked} are still at FLIP_LOCK_PERMS",
                (name,),
            ))


def _check_writes_per_flip(lab, artifact, out: List[Violation]) -> None:
    store = lab.server.store if lab.server is not None else None
    if store is None:
        return
    mutations = store.node_write_stats()["mutations"]
    sc = lab.scenario
    flips = sum(
        r.outcomes.get("success", 0) for r in lab.replicas.values()
    )
    # the contract: a flip costs ONE state-label unit plus (when
    # enabled) ONE evidence unit, because everything else rides those
    # carriers. Controller- and fault-issued writes are accounted
    # exactly, not hidden in the ratio.
    per_flip = 1 + (1 if sc.evidence else 0)
    budget = flips * per_flip
    # policy-driven waves: desired label + trace annotation per node,
    # plus the rollout record churn on the anchor node
    n_policies = len(_fault_entries(artifact, "policy_conflict")) * 2
    n_policies += sum(1 for f in artifact.get("faults") or []
                      if f.get("action") == "create_policy")
    budget += n_policies * (2 * sc.nodes + 32)
    if lab.injector is not None:
        budget += lab.injector.fault_write_units
        # an upgraded replica publishes one version annotation unit
        budget += lab.injector.upgraded_total
    budget += max(8, sc.nodes // 4)  # failed-state / repair slack
    if mutations > budget:
        ratio = mutations / max(1, flips)
        out.append(Violation(
            "writes_per_flip",
            f"{mutations} node-write mutation units for {flips} flips "
            f"({ratio:.2f}/flip) exceeds the coalescing budget of "
            f"{budget} — the flip path is issuing uncoalesced writes",
        ))


def sample_shard_leadership(shard_manager) -> Optional[Violation]:
    """One at-most-one-leader-per-shard probe: any partition whose
    lease is held by TWO live hosts simultaneously is a split brain.
    propgen's episode runner samples this during the run; check_run
    takes a final sample at quiescence."""
    if shard_manager is None:
        return None
    held: Dict[str, List[str]] = {}
    for host in getattr(shard_manager, "hosts", []):
        if not host.alive:
            continue
        hostname = getattr(host, "name", None) or repr(host)
        for sid in host.held_shards():
            held.setdefault(sid, []).append(hostname)
    dup = {sid: hosts for sid, hosts in held.items() if len(hosts) > 1}
    if dup:
        return Violation(
            "leader_uniqueness",
            f"shard partition(s) held by multiple live hosts: {dup}",
        )
    return None


def _check_forged_evidence(lab, artifact, out: List[Violation]) -> None:
    attest_lab = getattr(lab, "attest_lab", None)
    if attest_lab is None or not attest_lab.forged:
        return
    import json as _json

    from tpu_cc_manager.attest import judge_attestation

    reports = lab.final_fleet_reports()
    for entry in attest_lab.forged:
        node, claim, doc = entry["node"], entry["claim"], entry["doc"]
        verdict, detail = judge_attestation(doc, node)
        if verdict != "mismatch":
            out.append(Violation(
                "forged_evidence",
                f"{node}: forged document judged {verdict!r} "
                f"({detail}) — the measured-history contradiction was "
                "not read",
                (node,),
            ))
        # the forged claim must never have reached the silicon
        replica = lab.replicas.get(node)
        if replica is not None:
            flipped = [
                chip.path for chip in getattr(replica.backend, "chips", [])
                if chip.is_cc_query_supported
                and chip.query_cc_mode() == claim
            ]
            if flipped:
                out.append(Violation(
                    "forged_evidence",
                    f"{node}: device(s) {flipped} sit at the FORGED "
                    f"claim {claim!r} — a chip flipped on forged "
                    "evidence",
                    (node,),
                ))
        # if the forged document is still what the cluster serves, the
        # final audit must have flagged it (an honest later publish
        # replacing it is also acceptance-free — nothing to assert)
        store = lab.server.store if lab.server is not None else None
        if store is None or not reports:
            continue
        try:
            raw = (store.get_node(node)["metadata"].get("annotations")
                   or {}).get(L.EVIDENCE_ANNOTATION)
        except Exception:  # ccaudit: allow-swallow(post-run probe; a missing node/annotation means the forged doc is not live)
            raw = None
        planted = _json.dumps(doc, sort_keys=True,
                              separators=(",", ":"))
        if raw == planted:
            flagged = any(
                node in (audit.get("attestation_mismatch") or [])
                or node in (audit.get("invalid") or [])
                for audit in (
                    r.get("evidence_audit") or {} for r in reports
                )
            )
            if not flagged:
                out.append(Violation(
                    "forged_evidence",
                    f"{node}: forged document is live on the cluster "
                    "but the final fleet audit did not flag it",
                    (node,),
                ))


def _check_attestation_outage(lab, artifact,
                              out: List[Violation]) -> None:
    attest_lab = getattr(lab, "attest_lab", None)
    if attest_lab is None or not attest_lab.revoked:
        return
    revokes = _fault_entries(artifact, "root_revoked")
    if revokes and not any(f.get("armed_before_revoke")
                           for f in revokes):
        out.append(Violation(
            "attestation_outage",
            "the trust root was revoked before any fleet scan had "
            "verified a quote — the outage latch never armed, so the "
            "drill proved nothing (schedule the revocation later)",
        ))
        return
    reports = lab.final_fleet_reports()
    if not reports:
        out.append(Violation(
            "attestation_outage",
            "no fleet report available to judge the outage latch",
        ))
        return
    latched = False
    problem_line = False
    reverified = []
    for r in reports:
        audit = r.get("evidence_audit") or {}
        if audit.get("attestation_outage"):
            latched = True
        if any("attestation went unverifiable" in p
               for p in r.get("problems") or []):
            problem_line = True
        if audit.get("attestation_seen"):
            reverified.append(audit)
    if not latched:
        out.append(Violation(
            "attestation_outage",
            "verifier trust root revoked on a once-verified fleet but "
            "no final audit filled the attestation_outage bucket",
        ))
    if latched and not problem_line:
        out.append(Violation(
            "attestation_outage",
            "attestation_outage bucket filled but no fleet problems "
            "line surfaced it — the latch faded into a metric",
        ))
    if reverified:
        out.append(Violation(
            "attestation_outage",
            "a scan AFTER root revocation reported a verified quote — "
            "the fleet converged back to 'verified' without a trust "
            "root",
        ))


def _check_attestation_rotation(lab, artifact,
                                out: List[Violation]) -> None:
    attest_lab = getattr(lab, "attest_lab", None)
    if (attest_lab is None or attest_lab.rotations == 0
            or attest_lab.revoked):
        return
    import json as _json

    from tpu_cc_manager.attest import judge_attestation

    store = lab.server.store if lab.server is not None else None
    if store is None:
        return
    stale: List[str] = []
    broken: List[str] = []
    primary = attest_lab.key.encode()
    for name in sorted(lab.replicas):
        try:
            raw = (store.get_node(name)["metadata"].get("annotations")
                   or {}).get(L.EVIDENCE_ANNOTATION)
        except Exception:  # ccaudit: allow-swallow(post-run probe; unreadable evidence is counted in the broken bucket below)
            raw = None
        if not raw:
            broken.append(name)
            continue
        try:
            doc = _json.loads(raw)
        except ValueError:
            broken.append(name)
            continue
        verdict, _detail = judge_attestation(doc, name, key=primary)
        if verdict != "ok":
            stale.append(f"{name}({verdict})")
    if broken:
        out.append(Violation(
            "attestation_rotation",
            f"{len(broken)} node(s) have no judgeable evidence after "
            "the rotation wave",
            tuple(broken),
        ))
    if stale:
        out.append(Violation(
            "attestation_rotation",
            "settled evidence does not verify under the rotated "
            f"primary alone: {stale[:8]} — the fleet never finished "
            "re-quoting",
            tuple(s.split("(")[0] for s in stale),
        ))


def _check_region_attestation(lab, artifact,
                              out: List[Violation]) -> None:
    """The federation trust-domain boundary (ISSUE 16): judged from
    the artifact's ``metrics.federation.attestation`` block — the
    FederationLab has no single store or env-global attest lab, so the
    per-region audits ARE the evidence surface."""
    fed = (artifact.get("metrics") or {}).get("federation") or {}
    att = fed.get("attestation") or {}
    revokes = [f for f in _fault_entries(artifact, "root_revoked")
               if f.get("regions_revoked")]
    if not att or not revokes:
        return
    if not any(f.get("armed_before_revoke") for f in revokes):
        out.append(Violation(
            "region_attestation_latch",
            "the region root was revoked before any of its fleet scans "
            "had verified a quote — the latch never armed, so the "
            "drill proved nothing (schedule the revocation later)",
        ))
        return
    revoked_regions = set()
    for f in revokes:
        revoked_regions.update(f["regions_revoked"])
    for region, a in sorted(att.items()):
        if region in revoked_regions:
            if not a.get("revoked"):
                out.append(Violation(
                    "region_attestation_latch",
                    f"region {region}: root_revoked fired but the "
                    "region's trust domain reads unrevoked",
                ))
            if not a.get("attestation_outage"):
                out.append(Violation(
                    "region_attestation_latch",
                    f"region {region}: trust root revoked on a "
                    "once-verified region but its final audit filled "
                    "no attestation_outage bucket",
                ))
        else:
            # the non-spill half: a sibling's revocation must never
            # reach this region's verifier or its verified count
            if a.get("attestation_outage"):
                out.append(Violation(
                    "region_attestation_latch",
                    f"region {region}: attestation_outage latched "
                    "without a revocation — a sibling region's revoked "
                    "root spilled across the trust-domain boundary",
                    tuple(a.get("attestation_outage") or ()),
                ))
            if a.get("attestation_seen") and not a.get(
                    "attestation_verified"):
                out.append(Violation(
                    "region_attestation_latch",
                    f"region {region}: lost all quote verification "
                    "though its own root was never revoked",
                ))


def _check_policy_conflict(lab, artifact, out: List[Violation]) -> None:
    conflicts = _fault_entries(artifact, "policy_conflict")
    if not conflicts:
        return
    phases = (artifact.get("controllers") or {}).get(
        "policy_phases") or {}
    for entry in conflicts:
        owner, rival = entry.get("owner"), entry.get("rival")
        if rival is not None and phases.get(rival) != "Conflicted":
            out.append(Violation(
                "policy_conflict",
                f"rival policy {rival!r} ended in phase "
                f"{phases.get(rival)!r}, not Conflicted — an "
                "overlapping claim was acted on",
            ))
        if owner is not None and phases.get(owner) in (
                "Conflicted", "Invalid", "Degraded"):
            out.append(Violation(
                "policy_conflict",
                f"owner policy {owner!r} ended unhealthy "
                f"({phases.get(owner)!r}) — the conflict rule parked "
                "the wrong side",
            ))


def _check_upgrade(lab, artifact, out: List[Violation]) -> None:
    if not _fault_entries(artifact, "agent_upgrade"):
        return
    store = lab.server.store if lab.server is not None else None
    dead: List[str] = []
    unadvertised: List[str] = []
    for name, replica in sorted(lab.replicas.items()):
        if replica.version == "v1":
            continue
        if not replica.alive:
            dead.append(name)
            continue
        advertised = None
        if store is not None:
            try:
                advertised = (store.get_node(name)["metadata"]
                              .get("annotations") or {}).get(
                    L.AGENT_VERSION_ANNOTATION)
            except Exception:  # ccaudit: allow-swallow(post-run probe; an unreadable annotation counts as unadvertised below)
                advertised = None
        if advertised != replica.version:
            unadvertised.append(name)
    if dead:
        out.append(Violation(
            "upgrade_completeness",
            f"{len(dead)} upgraded replica(s) never came back up",
            tuple(dead),
        ))
    if unadvertised:
        out.append(Violation(
            "upgrade_completeness",
            f"{len(unadvertised)} upgraded replica(s) never "
            "advertised their new version (the cc.agent-version "
            "publication was lost)",
            tuple(unadvertised),
        ))


def _check_evacuation(lab, artifact, out: List[Violation]) -> None:
    if lab.injector is None or not lab.injector.evacuated_nodes:
        return
    store = lab.server.store if lab.server is not None else None
    if store is None:
        return
    cordoned = []
    for name in sorted(set(lab.injector.evacuated_nodes)):
        try:
            node = store.get_node(name)
        except Exception:  # ccaudit: allow-swallow(post-run probe; a vanished node cannot be cordoned)
            continue
        if (node.get("spec") or {}).get("unschedulable"):
            cordoned.append(name)
    if cordoned:
        out.append(Violation(
            "evacuation_restored",
            f"{len(cordoned)} node(s) left cordoned after the "
            "evacuation window",
            tuple(cordoned),
        ))


def _check_exposition(lab, artifact, out: List[Violation]) -> None:
    m = artifact.get("metrics") or {}
    shards = m.get("shards")
    if shards is not None and shards.get(
            "merged_exposition_problems") not in (None, 0):
        out.append(Violation(
            "exposition_valid",
            "merged /fleet/metrics exposition invalid "
            f"({shards['merged_exposition_problems']} problem(s))",
        ))
    slo = m.get("slo")
    if isinstance(slo, dict) and slo.get("aggregation_problems"):
        out.append(Violation(
            "exposition_valid",
            "fleet metrics aggregation invalid: "
            f"{slo['aggregation_problems'][:2]}",
        ))


def check_run(lab, artifact,
              extra: Optional[List[Violation]] = None
              ) -> List[Violation]:
    """Judge one finished simlab run against the whole catalog.
    ``lab`` is the (torn-down) SimLab instance — replicas, gate
    recordings, store, and controllers stay readable after run() —
    and ``artifact`` its return value. ``extra`` carries violations a
    live probe observed mid-run (e.g. propgen's shard-leadership
    sampler). Returns violations in catalog order, empty = green."""
    out: List[Violation] = list(extra or [])
    _check_convergence(lab, artifact, out)
    _check_half_flipped(lab, artifact, out)
    _check_fail_secure(lab, artifact, out)
    _check_writes_per_flip(lab, artifact, out)
    final_sample = sample_shard_leadership(
        getattr(lab, "shard_manager", None))
    if final_sample is not None:
        out.append(final_sample)
    _check_forged_evidence(lab, artifact, out)
    _check_attestation_outage(lab, artifact, out)
    _check_attestation_rotation(lab, artifact, out)
    _check_region_attestation(lab, artifact, out)
    _check_policy_conflict(lab, artifact, out)
    _check_upgrade(lab, artifact, out)
    _check_evacuation(lab, artifact, out)
    _check_exposition(lab, artifact, out)
    order = list(INVARIANTS)
    out.sort(key=lambda v: (order.index(v.invariant)
                            if v.invariant in order else len(order)))
    return out
