"""SimLab — orchestrates one scenario run end to end.

Assembly (all in one process, all over the real HTTP wire):

- a :class:`~tpu_cc_manager.k8s.apiserver.FakeApiServer` holding the
  scenario's node fleet;
- N :class:`~tpu_cc_manager.simlab.replica.ReplicaShell` live agents
  sharing one flow-controlled data-plane client, executed by a bounded
  :class:`~tpu_cc_manager.simlab.replica.WorkerPool`;
- ONE :class:`~tpu_cc_manager.simlab.pump.WatchPump` feeding every
  replica's mailbox from a single fleet-wide watch stream;
- optional fleet/policy controllers (with a leader-elected policy pair
  when the scenario says so), so policy-driven rollouts and fleet
  audits run concurrently with the agent churn;
- a :class:`~tpu_cc_manager.simlab.faults.FaultInjector` executing the
  scenario's scripted faults on schedule.

The run is judged by convergence: every node's observed-state label
reaching ``converge.mode`` within ``converge.timeout_s``, measured from
the first action that initiates the change. The artifact
(:mod:`~tpu_cc_manager.simlab.report`) carries the number the bench
trend gate compares plus the full diagnostic surface.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from tpu_cc_manager import labels as L
from tpu_cc_manager.device.fake import fake_backend
from tpu_cc_manager.k8s.apiserver import FakeApiServer
from tpu_cc_manager.k8s.client import HttpKubeClient, KubeConfig
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.obs import (
    Metrics, kube_throttle_wait_histogram, watch_pump_lag_histogram,
)
from tpu_cc_manager.flightrec import FlightRecorder, stitch_by_trace
from tpu_cc_manager.simlab.faults import FaultInjector
from tpu_cc_manager.simlab.pump import LagStamps, WatchPump
from tpu_cc_manager.simlab.replica import (
    _EMPTY as _REPLICA_EMPTY, ReplicaShell, WorkerPool,
)
from tpu_cc_manager.simlab.report import build_artifact
from tpu_cc_manager.simlab.scenario import Scenario
from tpu_cc_manager.trace import Tracer, format_traceparent, get_tracer

log = logging.getLogger("tpu-cc-manager.simlab")

#: the policy controllers' election Lease (must match __main__'s)
POLICY_LEASE = "tpu-cc-policy-controller"

#: pool-membership label on simlab nodes (scenario actions scope by it)
POOL_LABEL = "simlab.pool"


class AttestationLab:
    """Live attestation state for one scenario run: a software TPM per
    simulated node (own state dir, own measured flip history) plus the
    lab-provisioned VERIFIER trust root (TPU_CC_TPM_KEY for the run
    only — saved and restored), with key rotation and root revocation
    as first-class operations for the lifecycle faults.

    The split mirrors production exactly: the per-node TPMs are the
    node side (root can ask them to quote anything, cannot rewrite
    their history); the env key is the verifier side (the fleet
    audit's trust root). ``rotate`` moves both in the rotation posture
    (new primary + verify-only tail); ``revoke`` removes only the
    verifier side — the nodes keep quoting into the void, which is
    precisely the attestation_outage drill."""

    def __init__(self, node_names: List[str],
                 key_seed: str = "simlab-tpm-key"):
        import tempfile

        from tpu_cc_manager.attest import FakeTpm

        self._tmp = tempfile.TemporaryDirectory(prefix="simlab-tpm-")
        self._key_seed = key_seed
        self._seq = 0
        self._retired: List[str] = []
        self.key = f"{key_seed}-0"
        self.rotations = 0
        self.revoked = False
        #: (node, claim, doc) per planted node-root forgery
        self.forged: List[dict] = []
        self.tpms = {
            name: FakeTpm(
                state_dir=os.path.join(self._tmp.name, name),
                key=self.key.encode(),
            )
            for name in node_names
        }
        # ALL four sources attest.tpm_key()/tpm_keys() read are owned
        # for the run — an ambient TPU_CC_TPM_KEY_FILE on the host
        # would otherwise keep the verifier silently keyed straight
        # through a "revocation" (and pollute rotation tails)
        self._prior_env = {
            name: os.environ.get(name)
            for name in ("TPU_CC_TPM_KEY", "TPU_CC_TPM_OLD_KEYS",
                         "TPU_CC_TPM_KEY_FILE",
                         "TPU_CC_TPM_OLD_KEYS_FILE")
        }
        os.environ["TPU_CC_TPM_KEY"] = self.key
        for name in ("TPU_CC_TPM_OLD_KEYS", "TPU_CC_TPM_KEY_FILE",
                     "TPU_CC_TPM_OLD_KEYS_FILE"):
            os.environ.pop(name, None)

    def rotate(self) -> dict:
        self._seq += 1
        self._retired.insert(0, self.key)
        self.key = f"{self._key_seed}-{self._seq}"
        # verifier first — retired keys into the verify-only rotation
        # tail (TPU_CC_TPM_OLD_KEYS, attest.tpm_keys), new primary in —
        # then the signers: no ordering window where a fresh quote is
        # unverifiable
        os.environ["TPU_CC_TPM_OLD_KEYS"] = "\n".join(self._retired)
        os.environ["TPU_CC_TPM_KEY"] = self.key
        for tpm in self.tpms.values():
            tpm.set_key(self.key.encode())
        self.rotations += 1
        return {"rotation": self._seq, "tail_keys": len(self._retired)}

    def revoke(self) -> dict:
        # losing the PRIMARY is the whole outage: retired keys alone
        # keep a verifier keyless by attest.tpm_keys' rule. Every
        # source goes, including the file fallbacks cleared at
        # construction — belt and braces against a mid-run setter.
        for name in self._prior_env:
            os.environ.pop(name, None)
        self.revoked = True
        return {"revoked": True}

    def note_forged(self, node: str, claim: str, doc: dict) -> None:
        self.forged.append({"node": node, "claim": claim, "doc": doc})

    def close(self) -> None:
        for name, prior in self._prior_env.items():
            if prior is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = prior
        self._tmp.cleanup()


def _env_int(name: str, default: int) -> int:
    """Positive-int env override; unset, unparseable, or <= 0 (the
    documented '0 = scenario's value') falls back to the default."""
    try:
        value = int(os.environ.get(name, "") or 0)
    except ValueError:
        return default
    return value if value > 0 else default


class SimLab:
    def __init__(self, scenario: Scenario):
        self.scenario = scenario
        # TPU_CC_SIMLAB_WORKERS overrides the scenario's worker count
        # (config.py table) — the sandbox knob for "this host has more
        # cores than the scenario assumed"
        self.workers = _env_int("TPU_CC_SIMLAB_WORKERS",
                                scenario.workers)
        #: shared-loop replica I/O (ISSUE 13): when set, the fleet's
        #: data-plane client is a SyncKubeFacade over ONE AsyncKubeClient
        #: event loop instead of the threaded HttpKubeClient — env-keyed
        #: (not scenario schema) so ANY committed scenario can run in
        #: either I/O mode without a byte changing in scenarios/*.json
        self.shared_loop = os.environ.get(
            "TPU_CC_SIMLAB_SHARED_LOOP", ""
        ).lower() in ("1", "true", "yes")
        self.server: Optional[FakeApiServer] = None
        self.node_names: List[str] = []
        self.replicas: Dict[str, ReplicaShell] = {}
        self.pool: Optional[WorkerPool] = None
        self.pump: Optional[WatchPump] = None
        self.stamps = LagStamps()
        self.injector: Optional[FaultInjector] = None
        #: per-node TPMs + verifier trust root (scenario.attestation)
        self.attest_lab: Optional[AttestationLab] = None
        self._controller_threads: List[threading.Thread] = []
        self._controllers: List[object] = []
        #: tpu_cc_manager.shard.ShardManager when controllers.shards>0
        self.shard_manager = None
        #: shared node informer feeding the (non-sharded) policy
        #: controllers' scan wakes AND their rollouts' event-driven
        #: judges (ISSUE 14); the sharded plane brings its own
        self._policy_informer = None
        #: monotonic stamp of measured-convergence completion (the
        #: shard failover axis is kill -> this)
        self._conv_end_t: Optional[float] = None
        self._phase_durations: Dict[str, List[float]] = {}
        self._phase_lock = threading.Lock()
        self.tracer = Tracer()
        self.tracer.add_sink(self._phase_sink)
        # the driver's own black box: its desired_write spans are the
        # controller half of every stitched trace (ISSUE 8) — one per
        # set_mode action, so a small ring is plenty
        self.driver_rec = FlightRecorder(
            name="driver", span_ring=256, event_ring=128, sample_ring=8,
        )
        # policy-driven rollouts stamp their desired_write spans on the
        # PROCESS-default tracer (rollout.py get_tracer()), whose ring
        # the replica batchers' publish spans also churn through — a
        # post-run ring read would race eviction at 256 replicas. A
        # filtered sink captures exactly the controller spans as they
        # close; attached in run(), detached in _teardown.
        self.ctrl_rec = FlightRecorder(
            name="controller", span_ring=256, event_ring=8, sample_ring=8,
        )

        def _ctrl_sink(span) -> None:
            if span.name == "desired_write":
                self.ctrl_rec.observe_span(span)

        self._ctrl_sink = _ctrl_sink
        # the fleet observatory (fleetobs.py, ISSUE 9): scrapes every
        # replica's metric set in-process on an interval, merges the
        # fleet exposition (validated), and burns SLO budgets from
        # deployments/slo.yaml. Its alert events note into a dedicated
        # black box collected with the rest of the recordings.
        self.observer = None
        self.slo_skipped: Optional[str] = None
        self.obs_rec = FlightRecorder(
            name="fleetobs", span_ring=8, event_ring=64, sample_ring=8,
        )
        # the fleet-level anomaly watchdog (watchdog.py, ISSUE 15):
        # rides the observer's merged sample history — one detector
        # over the whole fleet's windowed series instead of N per-
        # replica sampling threads. Incidents (exemplar trace ids +
        # live profile + black-box note) land in the artifact, with
        # each exemplar resolved against the fleet-wide trace stitch.
        self.watchdog = None
        self.profiler = None
        self.lag_hist = watch_pump_lag_histogram()
        self.throttle_hist = kube_throttle_wait_histogram()
        self._throttle_samples: List[float] = []
        self._throttle_lock = threading.Lock()

    # ------------------------------------------------------------ plumbing
    def _phase_sink(self, span) -> None:
        with self._phase_lock:
            self._phase_durations.setdefault(span.name, []).append(
                span.dur_s
            )

    def _observe_throttle(self, waited: float) -> None:
        self.throttle_hist.observe(waited)
        if waited > 0:
            with self._throttle_lock:
                self._throttle_samples.append(waited)

    def _client(self, qps: float = 0.0) -> HttpKubeClient:
        return HttpKubeClient(
            KubeConfig("127.0.0.1", self.server.port, use_tls=False),
            qps=qps,
        )

    def _pool_of(self, i: int) -> str:
        return f"p{i % self.scenario.pools}"

    # -------------------------------------------------------------- setup
    def _build_fleet(self) -> None:
        sc = self.scenario
        store = self.server.store
        self.node_names = [f"sim-{i:04d}" for i in range(sc.nodes)]
        for i, name in enumerate(self.node_names):
            store.add_node(make_node(name, labels={
                L.TPU_ACCELERATOR_LABEL: "tpu-v5p-slice",
                POOL_LABEL: self._pool_of(i),
                L.CC_MODE_LABEL: sc.initial_mode,
            }))
        if sc.attestation:
            self.attest_lab = AttestationLab(self.node_names)
        for name in self.node_names:
            self.replicas[name] = ReplicaShell(
                name, self.data_kube,
                fake_backend(n_chips=sc.chips_per_node),
                self.tracer, evidence=sc.evidence,
                metrics=Metrics(),
                attestor=(self.attest_lab.tpms[name]
                          if self.attest_lab is not None else None),
            )

    def _start_observer(self) -> None:
        """Build + start the SLO observer over every replica's metric
        render (in-process scrape — zero HTTP load on the system under
        test, and zero node writes by construction). Degrades loudly
        to a skipped block when pyyaml or slo.yaml is unavailable —
        observability must never fail a scenario on its own."""
        from tpu_cc_manager import fleetobs

        try:
            objectives = fleetobs.load_slo(fleetobs.default_slo_path())
        except ImportError:
            self.slo_skipped = "pyyaml not installed"
            log.warning("slo engine skipped: pyyaml not installed")
            return
        except fleetobs.SloError as e:
            self.slo_skipped = f"slo.yaml invalid: {e}"
            log.warning("slo engine skipped: %s", e)
            return
        self.observer = fleetobs.FleetObserver(
            objectives, name=self.scenario.name, recorder=self.obs_rec,
        )
        if os.environ.get("TPU_CC_SIMLAB_WATCHDOG", "1").lower() not in (
                "0", "false", "no"):
            from tpu_cc_manager.profiler import SamplingProfiler
            from tpu_cc_manager.watchdog import Watchdog

            self.profiler = SamplingProfiler(name="simlab")
            self.watchdog = Watchdog(
                sources=[r.metrics for r in self.replicas.values()],
                profiler=self.profiler, recorder=self.obs_rec,
                name=self.scenario.name,
            )
            self.observer.add_listener(self.watchdog.consume)
        self.observer.start(
            [r.metrics.render for r in self.replicas.values()]
        )

    def _start_controllers(self) -> None:
        sc = self.scenario
        if sc.controllers.shards:
            # sharded control plane (ISSUE 11): N consistent-hash
            # controller shards over ONE shared node informer — each
            # shard a per-lease FleetController (and PolicyController
            # when the scenario runs the policy plane) scoped to its
            # pool partition; /fleet/metrics merges shard expositions
            from tpu_cc_manager.shard import ShardManager

            self.shard_manager = ShardManager(
                lambda: self._client(qps=0),
                shards=sc.controllers.shards,
                pools=[f"p{i}" for i in range(sc.pools)],
                pool_label=POOL_LABEL,
                policy=sc.controllers.policy,
                fleet_interval_s=5.0,
                policy_interval_s=1.0,
                verify_evidence=sc.evidence,
            )
            self.shard_manager.start()
            if not self.shard_manager.wait_covered(timeout_s=15.0):
                log.warning(
                    "shard plane did not reach full partition coverage "
                    "before the timeline; continuing (coverage: %s)",
                    self.shard_manager.coverage(),
                )
            return
        if sc.controllers.fleet:
            from tpu_cc_manager.fleet import FleetController

            fleet = FleetController(
                self._client(qps=sc.qps), interval_s=5.0, port=0,
                observer=self.observer,
            )
            self._controllers.append(fleet)
            t = threading.Thread(target=fleet.run, daemon=True,
                                 name="simlab-fleet")
            t.start()
            self._controller_threads.append(t)
        if sc.controllers.policy:
            from tpu_cc_manager.policy import PolicyController
            from tpu_cc_manager.watch import NodeInformer

            # ONE shared informer for every policy replica (the shard
            # plane has its own): feeds the controllers' node wakes
            # and their rollouts' delta-judged windows (ISSUE 14), so
            # in-scenario rollout judging adds zero LIST load to the
            # faulted API server
            informer = NodeInformer(self._client(qps=0),
                                    name="simlab-policy")
            try:
                informer.prime()
            except Exception:
                log.warning("simlab policy informer prime failed; "
                            "priming from the watch thread",
                            exc_info=True)
            self._policy_informer = informer.start()

            n = 2 if sc.controllers.leader_elect else 1
            for i in range(n):
                elector = None
                kube = self._client(qps=sc.qps)
                if sc.controllers.leader_elect:
                    from tpu_cc_manager.leader import LeaderElector

                    # short terms so a flapped lease re-resolves inside
                    # scenario time; elector traffic rides an unlimited
                    # client like __main__._leader_elector does
                    elector = LeaderElector(
                        self._client(qps=0),
                        name=POLICY_LEASE,
                        identity=f"simlab-policy-{i}",
                        namespace="tpu-system",
                        lease_duration_s=2.0,
                        renew_period_s=0.5,
                        retry_period_s=0.25,
                    )
                ctrl = PolicyController(
                    kube, interval_s=1.0, port=0, poll_s=0.05,
                    verify_evidence=sc.evidence,
                    leader_elector=elector,
                    adopt_after_s=2.0,
                    informer=self._policy_informer,
                )
                self._controllers.append(ctrl)
                t = threading.Thread(target=ctrl.run, daemon=True,
                                     name=f"simlab-policy-{i}")
                t.start()
                self._controller_threads.append(t)

    # ------------------------------------------------------------- actions
    def _nodes_in_pool(self, pool: Optional[int]) -> List[str]:
        if pool is None:
            return self.node_names
        tag = f"p{pool}"
        return [
            name for i, name in enumerate(self.node_names)
            if self._pool_of(i) == tag
        ]

    def _act_set_mode(self, params: dict) -> dict:
        mode = params["mode"]
        names = self._nodes_in_pool(params.get("pool"))
        # ONE desired_write span per action, stamped as the cc.trace
        # annotation in the SAME store write as the desired label —
        # exactly the real controller contract (rollout.launch_group).
        # Every replica reconcile triggered by this action adopts the
        # context, so the fleet-wide stitch joins driver and replicas
        # on this span's trace id.
        with self.tracer.span(
            "desired_write", mode=mode, nodes=len(names),
            pool=params.get("pool"),
        ) as span:
            context = format_traceparent(span)
            for name in names:
                self.stamps.record(name, mode, time.monotonic())
                # out-of-band store write (like _wait_converged's
                # polling): the driver's input must neither add HTTP
                # load to the system under test nor soak a scripted
                # write_429 storm
                self.server.store.set_node_labels_direct(
                    name, {L.CC_MODE_LABEL: mode},
                    annotations={L.CC_TRACE_ANNOTATION: context},
                )
        self.driver_rec.observe_span(span)
        return {"mode": mode, "nodes": len(names),
                "trace_id": span.trace_id}

    def _create_policy(self, *, mode: str, pool: Optional[int],
                       name: Optional[str] = None,
                       max_unavailable: Optional[int] = None,
                       group_timeout_s: float = 120) -> dict:
        """Create one TPUCCPolicy CR in the store (shared by the
        create_policy action and the policy_conflict fault)."""
        selector = (f"{POOL_LABEL}=p{pool}" if pool is not None
                    else L.TPU_ACCELERATOR_LABEL)
        names = self._nodes_in_pool(pool)
        if max_unavailable is None:
            max_unavailable = len(names)
        if name is None:
            name = (f"simlab-{self.scenario.name}-"
                    f"{pool if pool is not None else 'all'}")
        self.server.store.add_custom(L.POLICY_GROUP, L.POLICY_PLURAL, {
            "apiVersion": f"{L.POLICY_GROUP}/{L.POLICY_VERSION}",
            "kind": L.POLICY_KIND,
            "metadata": {"name": name},
            "spec": {
                "mode": mode,
                "nodeSelector": selector,
                "strategy": {
                    "maxUnavailable": max_unavailable,
                    "groupTimeoutSeconds": group_timeout_s,
                },
            },
        })
        return {"policy": name, "mode": mode, "selector": selector}

    def _act_create_policy(self, params: dict) -> dict:
        return self._create_policy(
            mode=params["mode"],
            pool=params.get("pool"),
            max_unavailable=params.get("max_unavailable"),
            group_timeout_s=params.get("group_timeout_s", 120),
        )

    # --------------------------------------------------- fleet plane taps
    def _fleet_controllers(self) -> List[object]:
        from tpu_cc_manager.fleet import FleetController

        ctls = [c for c in self._controllers
                if isinstance(c, FleetController)]
        if self.shard_manager is not None:
            ctls.extend(b.fleet for b in self.shard_manager.bundles())
        return ctls

    def _attestation_armed(self) -> bool:
        """Has any fleet scan verified a TEE quote yet? (The
        root_revoked fault waits for this — the outage latch only
        fires on a once-verified fleet.)"""
        return any(
            getattr(c, "attestation_ever_verified", False)
            for c in self._fleet_controllers()
        )

    def final_fleet_reports(self) -> List[dict]:
        """Every fleet controller's last report (after the settle
        scan) — the invariants oracle judges audit buckets and
        problems lines from these."""
        return [c.last_report for c in self._fleet_controllers()
                if getattr(c, "last_report", None)]

    # --------------------------------------------------------- convergence
    def _wait_converged(self, target: str, timeout_s: float):
        """(elapsed_s or None, pending names). Polls the store directly
        — measurement must not add HTTP load to the system under
        test."""
        store = self.server.store
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        pending = set(self.node_names)
        while pending and time.monotonic() < deadline:
            pending = {
                n for n in pending
                if store.peek_node_label(
                    n, L.CC_MODE_STATE_LABEL) != target
            }
            if pending:
                time.sleep(0.05)
        if pending:
            return None, sorted(pending)
        return time.monotonic() - t0, []

    # ----------------------------------------------------------------- run
    def run(self) -> dict:
        sc = self.scenario
        # the CLI path has no conftest: keep platform identity and
        # attestation probes out of a lab run unless explicitly set
        os.environ.setdefault("TPU_CC_IDENTITY", "none")
        os.environ.setdefault("TPU_CC_ATTESTATION", "none")
        log.info("simlab: scenario %r — %d nodes / %d pools / "
                 "%d workers / qps=%s", sc.name, sc.nodes, sc.pools,
                 self.workers, sc.qps or "off")
        self.server = FakeApiServer().start()
        get_tracer().add_sink(self._ctrl_sink)
        notes = None
        faults: List[dict] = []
        try:
            if self.shared_loop:
                # opt-in shared-loop mode (ISSUE 13,
                # TPU_CC_SIMLAB_SHARED_LOOP): every replica's
                # publish/state writes multiplex ONE event loop's
                # pipelined connection pool (k8s/aio.py) through a
                # sync façade, instead of checking thread-private
                # sockets out of the threaded client's pool — the
                # 1,024-replica fleet exercises the same I/O core the
                # agent opts into with TPU_CC_KUBE_AIO. Same throttle
                # surface, so faults' set_qps squeezes and the
                # artifact's throttle block work unchanged.
                from tpu_cc_manager.k8s.aio_bridge import SyncKubeFacade

                self.data_kube = SyncKubeFacade(
                    KubeConfig("127.0.0.1", self.server.port,
                               use_tls=False),
                    qps=sc.qps,
                )
            else:
                self.data_kube = self._client(qps=sc.qps)
            self.data_kube.add_throttle_observer(self._observe_throttle)
            self.ops_kube = self._client(qps=0)
            self._build_fleet()
            self.pool = WorkerPool(self.replicas, self.workers).start()
            self.pump = WatchPump(
                self._client(qps=0), self.replicas, self.pool,
                self.stamps, self.lag_hist,
                watch_timeout_s=sc.watch_timeout_s,
            )
            self.pump.prime()
            self.pump.start()
            self.injector = FaultInjector(
                store=self.server.store,
                replicas=self.replicas,
                pool=self.pool,
                data_kube=self.data_kube,
                ops_kube=self.ops_kube,
                base_qps=sc.qps,
                lease_names=(
                    [POLICY_LEASE] if sc.controllers.leader_elect else []
                ),
                nodes_in_pool=self._nodes_in_pool,
                attest_lab=self.attest_lab,
                create_policy=self._create_policy,
                attestation_armed=self._attestation_armed,
                converge_mode=sc.converge.mode,
            )

            # initial reconcile: one deliberate storm to initial_mode,
            # outside the measurement (the bench's wait_all("off") analog)
            for name in self.node_names:
                self.pool.submit(name, sc.initial_mode)
            initial_s, pending = self._wait_converged(
                sc.initial_mode, min(60.0, sc.converge.timeout_s)
            )
            if initial_s is None:
                notes = (f"{len(pending)} replicas never initialized "
                         f"to {sc.initial_mode!r}")
                return self._finish(False, None, None, pending, faults,
                                    notes)
            # observer starts AFTER the initial convergence storm: the
            # SLO budgets judge the scenario timeline, not the lab's
            # own setup traffic
            self._start_observer()
            self._start_controllers()
            if self.shard_manager is not None:
                self.injector.shard_manager = self.shard_manager

            # ---- the timeline (actions are pre-sorted by `at`)
            t0 = time.monotonic()
            t_change: Optional[float] = None
            for action in sc.actions:
                delay = t0 + action.at - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                rel_t = time.monotonic() - t0
                if action.kind == "fault":
                    params = dict(action.params)
                    kind = params.pop("fault")
                    faults.append(
                        self.injector.inject(kind, params, rel_t)
                    )
                    continue
                if action.kind == "set_mode":
                    entry = self._act_set_mode(action.params)
                else:
                    entry = self._act_create_policy(action.params)
                entry.update({"at_s": round(rel_t, 3),
                              "action": action.kind})
                faults.append(entry)
                if (t_change is None
                        and action.params["mode"] == sc.converge.mode):
                    t_change = time.monotonic()

            conv_s, pending = self._wait_converged(
                sc.converge.mode, sc.converge.timeout_s
            )
            if conv_s is not None:
                self._conv_end_t = time.monotonic()
            if conv_s is not None and t_change is not None:
                # convergence is change-initiation -> last node, not
                # wait-start -> last node (actions after the initiating
                # one consumed timeline seconds the fleet was already
                # converging through)
                conv_s = time.monotonic() - t_change
            ok = conv_s is not None
            if ok:
                # AFTER the measurement: settle time (straggler drain +
                # the final fleet scan) must not inflate the trend-gated
                # convergence number
                self._settle()
            if not ok:
                notes = (f"{len(pending)} nodes never reached "
                         f"{sc.converge.mode!r} within "
                         f"{sc.converge.timeout_s}s")
            return self._finish(ok, initial_s, conv_s, pending, faults,
                                notes)
        finally:
            self._teardown()

    def _settle(self) -> None:
        """After convergence: drain straggler work (the state label
        lands before that reconcile's evidence write), then run one
        final fleet scan so the artifact's audit reflects the settled
        fleet — mid-churn skew (evidence a throttled write behind its
        label) is the scan racing the storm, not an end-state
        finding."""
        if self.injector is not None:
            # restorative fault callbacks (uncordon, throttle restore)
            # run early: the settled fleet the oracle judges must be
            # the restored one even when convergence beat the delay
            self.injector.settle()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            busy = any(
                r._queued or r._pending is not _REPLICA_EMPTY
                for r in self.replicas.values()
            )
            if not busy:
                break
            time.sleep(0.05)
        # deliver deferred publications that found no carrier (the last
        # reconcile's evidence has no next state write to ride): the
        # final fleet scan below must audit the settled fleet, and the
        # newest-generation-always-lands contract is judged here
        for r in self.replicas.values():
            r.batcher.flush()
        for c in self._controllers:
            from tpu_cc_manager.fleet import FleetController

            if isinstance(c, FleetController):
                try:
                    c.scan_once()
                except Exception:
                    log.warning("final fleet scan failed",
                                exc_info=True)
        if self.shard_manager is not None:
            for bundle in self.shard_manager.bundles():
                try:
                    bundle.fleet.scan_once()
                except Exception:
                    log.warning("final shard fleet scan failed",
                                exc_info=True)

    # ------------------------------------------------------ trace stitch
    def _stitch_traces(self) -> "tuple[dict, dict]":
        """Collect every process-local flight recording (driver +
        controllers + all replicas), stitch spans fleet-wide by trace
        id, and derive the end-to-end convergence distribution: for
        each desired-write trace, per node, label-commit
        (``desired_write`` span start) → that node's LAST adopted
        ``reconcile`` span end (the state publish happens inside it).
        This is the cross-process latency ROADMAP item 2 asks for —
        measured from causal traces, not from the driver's poll.
        Returns (summary block, the full stitched map) — the incident
        packets' exemplar trace ids are resolved against the map."""
        recordings = [self.driver_rec.snapshot("run_end"),
                      self.ctrl_rec.snapshot("run_end"),
                      self.obs_rec.snapshot("run_end")]
        for r in self.replicas.values():
            recordings.append(r.recorder.snapshot("run_end"))
        stitched = stitch_by_trace(recordings)
        from tpu_cc_manager.simlab.report import percentile

        samples: List[float] = []
        cross = 0
        example: List[dict] = []
        for spans in stitched.values():
            recorders = {s.get("recorder") for s in spans
                         if s.get("recorder")}
            desired = [s for s in spans if s["name"] == "desired_write"]
            if len(recorders) > 1 and desired:
                cross += 1
                if len(spans) > len(example):
                    example = spans
            if not desired:
                continue
            t0 = min(s["start_ts"] for s in desired)
            ends: Dict[str, float] = {}
            for s in spans:
                if s["name"] != "reconcile":
                    continue
                node = ((s.get("attrs") or {}).get("node")
                        or s.get("recorder"))
                end = s["start_ts"] + s["dur_s"]
                if node and end > ends.get(node, 0.0):
                    ends[node] = end
            samples.extend(
                max(0.0, end - t0) for end in ends.values()
            )
        return {
            "traces": len(stitched),
            "cross_process_traces": cross,
            "e2e_samples": len(samples),
            "e2e_convergence_p50_s": percentile(samples, 0.50),
            "e2e_convergence_p99_s": percentile(samples, 0.99),
            # one stitched fleet timeline as evidence the propagation
            # works end to end (capped: the artifact must stay small)
            "timeline_example": example[:12],
        }, stitched

    def _incidents_block(self, stitched: dict) -> Optional[dict]:
        """The watchdog's autopsy record for the artifact (ISSUE 15):
        each packet's exemplar trace ids resolved against the
        fleet-wide stitch — ``resolved_trace_ids`` are ids present in
        the stitched map at all, ``cross_process_trace_ids`` the
        subset whose span bucket spans more than one recorder (the
        incident demonstrably joins a controller's desired write to a
        replica's slow reconcile)."""
        if self.watchdog is None:
            return None
        cross_ids = {
            tid for tid, spans in stitched.items()
            if len({s.get("recorder") for s in spans
                    if s.get("recorder")}) > 1
        }
        packets = []
        for p in self.watchdog.incidents():
            p = dict(p)
            tids = {
                e.get("trace_id") for e in (p.get("exemplars") or [])
                if e.get("trace_id")
            }
            p["resolved_trace_ids"] = sorted(tids & set(stitched))
            p["cross_process_trace_ids"] = sorted(tids & cross_ids)
            packets.append(p)
        return {
            "count": self.watchdog.incidents_total,
            "last_capture_s": self.watchdog.last_capture_s,
            "packets": packets[-8:],
        }

    def _finish(self, ok, initial_s, conv_s, pending, faults, notes):
        replica_stats = {"total": 0, "repairs": 0, "coalesced": 0}
        # the coalescing publish core's loss accounting, fleet-wide
        # (ISSUE 6): superseded/folded/flushed/retried/dropped
        # publications across every replica batcher
        publish_stats = {"coalesced": 0, "folded": 0, "flushed": 0,
                         "retries": 0, "dropped": 0, "pending": 0}
        for r in self.replicas.values():
            replica_stats["total"] += r.reconciles
            replica_stats["repairs"] += r.repairs
            replica_stats["coalesced"] += r.coalesced
            for outcome, n in r.outcomes.items():
                replica_stats[outcome] = (
                    replica_stats.get(outcome, 0) + n
                )
            for k, v in r.batcher.stats().items():
                publish_stats[k] = publish_stats.get(k, 0) + v
        replica_stats["publish"] = publish_stats
        # HTTP round trips vs the logical mutations they carried: the
        # gap is the batching win; per-request numbers without this
        # split would silently inflate under coalescing
        if self.server is not None:
            replica_stats["api_writes"] = (
                self.server.store.node_write_stats()
            )
        from tpu_cc_manager.simlab.report import percentile

        with self._throttle_lock:
            waits = list(self._throttle_samples)
        throttle = {
            "waits": self.data_kube.throttle_waits,
            "wait_s_total": round(
                self.data_kube.throttle_wait_s_total, 4),
            "wait_p50_s": percentile(waits, 0.50),
            "wait_max_s": round(max(waits), 5) if waits else None,
            "histogram": self.throttle_hist.snapshot(),
        }
        # which I/O core served the fleet's data plane — with the
        # async core's own accounting (dials vs requests is the
        # multiplexing win; replays prove the exactly-once path)
        kube_io = {"core": "aio" if self.shared_loop else "threaded"}
        if self.shared_loop:
            kube_io.update(self.data_kube.stats())
        controllers = {"running": len(self._controllers)}
        for c in self._controllers:
            report = getattr(c, "last_report", None) or {}
            # the policy controller's report keys policies by name; the
            # fleet controller's carries a list of policy summaries
            policies = report.get("policies")
            if isinstance(policies, dict):
                phases = {
                    name: (st or {}).get("phase")
                    for name, st in policies.items()
                }
                if phases:
                    controllers.setdefault("policy_phases", {}).update(
                        phases)
            if "problems" in report:
                # headline-capped: a fleet-wide finding enumerates every
                # node and would dwarf the artifact
                controllers["fleet_problems"] = [
                    p if len(p) <= 160 else p[:160] + "..."
                    for p in report["problems"][:5]
                ]
                controllers["fleet_problem_count"] = len(
                    report["problems"])
        if self.injector is not None:
            replica_stats["crashed"] = self.injector.crashed_total
            replica_stats["restarted"] = self.injector.restarted_total
        # lifecycle surface (ISSUE 12): versions running at quiescence,
        # upgrade/evacuation accounting, and the attestation lab state
        # — the invariants oracle reads the live lab, but the artifact
        # must carry enough for a regression reader too
        versions: Dict[str, int] = {}
        for r in self.replicas.values():
            versions[r.version] = versions.get(r.version, 0) + 1
        lifecycle = {"versions": versions}
        if self.injector is not None:
            lifecycle["upgraded"] = self.injector.upgraded_total
            lifecycle["evacuated"] = len(self.injector.evacuated_nodes)
        if self.attest_lab is not None:
            lifecycle["attestation"] = {
                "rotations": self.attest_lab.rotations,
                "revoked": self.attest_lab.revoked,
                "forged_nodes": [f["node"]
                                 for f in self.attest_lab.forged],
            }
        shards = None
        if self.shard_manager is not None:
            from tpu_cc_manager.obs import validate_exposition

            killed = bool(self.injector is not None
                          and self.injector.last_shard_kill_t)
            handoffs_done = False
            if killed:
                # the fleet may converge before the dead shard's lease
                # ripens: the failover axis judges control-plane
                # recovery too, so wait (bounded) for the coverage
                # monitor to stamp every handoff
                handoffs_done = self.shard_manager.wait_failovers(
                    timeout_s=30.0
                )
            merged = self.shard_manager.merged_fleet_metrics()
            stats = self.shard_manager.stats()
            shards = {
                "stats": stats,
                # the one-fleet-view contract: the merged per-shard
                # /fleet/metrics must itself be a valid exposition
                "merged_exposition_problems": len(
                    validate_exposition(merged)
                ),
            }
            if killed and self._conv_end_t is not None:
                # the ISSUE 11 failover axis: shard kill -> BOTH every
                # node at the target mode AND the orphaned partition
                # re-held by a survivor (whichever lands later). A
                # handoff that never completed must leave the axis
                # ABSENT (None downstream) — agents converge
                # autonomously, so stamping convergence alone would
                # let a broken lease takeover pass as a small, green
                # number on the exact axis that gates it (bench.py and
                # shard_smoke both fail loudly on None).
                handoffs = [
                    f["handoff_s"] for f in stats["failovers"]
                    if f["handoff_s"] is not None
                ]
                if handoffs_done and handoffs:
                    shards["failover_convergence_s"] = round(max(
                        max(0.0, self._conv_end_t
                            - self.injector.last_shard_kill_t),
                        max(handoffs),
                    ), 4)
                else:
                    log.error(
                        "shard failover never completed: %s",
                        stats["failovers"],
                    )
        # final SLO state: one closing observe() so the artifact's
        # budget/alert story includes everything through settle, then
        # the engine's summary (or the honest skip reason)
        if self.observer is not None:
            try:
                self.observer.observe(
                    [r.metrics.render for r in self.replicas.values()]
                )
            except Exception:
                log.warning("closing slo observe failed", exc_info=True)
            slo = self.observer.summary()
        else:
            slo = {"skipped": self.slo_skipped or "observer not started"}
        with self._phase_lock:
            phase_durations = {
                k: list(v) for k, v in self._phase_durations.items()
            }
        trace_stitch, stitched = self._stitch_traces()
        return build_artifact(
            self.scenario,
            ok=ok,
            initial_convergence_s=initial_s,
            convergence_s=conv_s,
            pending=pending,
            pump_stats=(self.pump.stats() if self.pump else {}),
            throttle=throttle,
            phase_durations=phase_durations,
            replica_stats=replica_stats,
            faults=faults,
            controllers=controllers,
            trace_stitch=trace_stitch,
            slo=slo,
            incidents=self._incidents_block(stitched),
            shards=shards,
            lifecycle=lifecycle,
            kube_io=kube_io,
            notes=notes,
        )

    def _teardown(self) -> None:
        get_tracer().remove_sink(self._ctrl_sink)
        if self.shared_loop and getattr(self, "data_kube", None) is not None:
            # reclaim the shared loop's pooled connections (and their
            # reader tasks) — the bridge loop itself outlives the run
            try:
                self.data_kube.close()
            except Exception:
                log.warning("shared-loop client close failed",
                            exc_info=True)
        if self.observer is not None:
            self.observer.stop()
        if self.injector is not None:
            self.injector.cancel()
        for c in self._controllers:
            try:
                c.stop()
            except Exception:
                log.warning("controller stop failed", exc_info=True)
        if self.shard_manager is not None:
            try:
                self.shard_manager.stop()
            except Exception:
                log.warning("shard manager stop failed", exc_info=True)
        if self._policy_informer is not None:
            try:
                self._policy_informer.stop()
            except Exception:
                log.warning("policy informer stop failed", exc_info=True)
        for t in self._controller_threads:
            t.join(timeout=5)
        if self.pump is not None:
            self.pump.stop()
        if self.pool is not None:
            self.pool.stop()
        if self.server is not None:
            self.server.stop()
        if self.attest_lab is not None:
            # restores the process's prior TPU_CC_TPM_KEY posture and
            # removes the per-node TPM state dirs
            self.attest_lab.close()
