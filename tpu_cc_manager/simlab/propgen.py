"""Property-based lifecycle scenario generation (ISSUE 12).

simlab's committed scenarios test the failures we already imagined.
This module is the machine that finds the interleaving we didn't: a
SEEDED generator composes random timelines of infrastructure faults
(watch drops, crashes, 429 storms, shard kills) with the four
lifecycle fault families — rolling agent upgrades, attestation key
rotation / revoked trust root (with the node-root forgery drill),
overlapping-policy conflicts, and evacuation drains racing flips —
runs every episode through the live simlab harness, and judges it
with the reusable convergence-and-invariants oracle
(:mod:`simlab.invariants`).

On a violation the episode SHRINKS — QuickCheck/ddmin style: drop
fault events, then pull them earlier (reorder), re-running after each
edit and keeping only edits that still reproduce the same broken
invariant. The shrink order is derived from the seed, so a find
shrinks the same way twice. Every find is emitted as a replayable
``scenarios/gen-<seed>.json`` (canonical formatting — the file is a
first-class scenario, runnable with ``simlab run`` and promotable to a
named scenario by committing it) plus a report sidecar carrying the
violations and the stitched flight-recorder timeline.

Determinism contract: ``generate_episode(seed)`` is a pure function of
the seed (and the optional family override). The RUN of an episode is
real concurrent execution — the generator finds interleavings, it does
not fake them — so reproduction is probabilistic the way Jepsen's is:
same seed, same timeline, same faults, re-raced. The shrinker
re-verifies every step against a live re-run for exactly that reason.

CLI: ``python -m tpu_cc_manager simlab propgen --seeds 1,2,3``; the
``propgen-smoke`` CI job runs a fixed seed list through all four
families and requires zero violations (scripts/propgen_smoke.py).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import threading
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

from tpu_cc_manager.simlab.invariants import (
    Violation, check_run, sample_shard_leadership,
)
from tpu_cc_manager.simlab.scenario import (
    ScenarioError, canonical_scenario_text, validate_scenario,
)

log = logging.getLogger("tpu-cc-manager.simlab.propgen")

#: the lifecycle fault families the generator composes (ISSUE 12);
#: "attestation" covers both the key_rotation and root_revoked drills.
#: "federation" (ISSUE 16) generates schema-2 multi-region episodes —
#: region partitions/blackouts/latency skews, region evacuations, and
#: the region-scoped revoked-root drill — run through FederationLab.
FAMILIES = ("upgrade", "attestation", "policy", "evacuation", "shards",
            "federation")

#: desired modes the generator draws from (never "ici": slice
#: semantics need multi-host topology the generated fleets don't have)
_MODES = ("on", "devtools", "off")

#: default convergence budget for generated episodes — generous, the
#: oracle's convergence invariant is about EVENTUAL convergence, not
#: speed (the bench axes judge speed)
_TIMEOUT_S = 75.0


def _rng(tag: str, seed: int) -> random.Random:
    return random.Random(f"tpu-cc-propgen-{tag}-{seed}")


# ---------------------------------------------------------- generation
def _pick_modes(rng: random.Random) -> Tuple[str, str]:
    """(intermediate wave mode, converge mode), distinct; the converge
    target is never the 'off' initial state, so convergence is a real
    fleet-wide change."""
    converge = rng.choice(("on", "devtools"))
    wave = rng.choice([m for m in _MODES if m != converge])
    return wave, converge


def _infra_extras(rng: random.Random, nodes: int) -> List[dict]:
    """0-2 composable infrastructure faults sprinkled into the early
    timeline — the generator's job is interleavings, and lifecycle
    events rarely get a quiet fleet."""
    pool = [
        {"action": "fault", "fault": "watch_drop", "count": 2},
        {"action": "fault", "fault": "agent_crash",
         "count": max(1, nodes // 4), "restart_after_s": 0.8},
        {"action": "fault", "fault": "write_429", "count": 20},
        {"action": "fault", "fault": "list_429", "count": 1},
        {"action": "fault", "fault": "watch_410"},
        {"action": "fault", "fault": "throttle_squeeze", "qps": 10,
         "duration_s": 0.5},
    ]
    extras = []
    for entry in rng.sample(pool, rng.randrange(0, 3)):
        entry = dict(entry)
        entry["at"] = round(rng.uniform(0.0, 0.6), 2)
        extras.append(entry)
    return extras


def generate_episode(seed: int,
                     families: Optional[Iterable[str]] = None) -> dict:
    """One scenario document, a pure function of ``seed`` (same seed →
    byte-identical doc). ``families`` overrides the seeded family
    choice — the smoke uses it to guarantee coverage of all four."""
    rng = _rng("gen", seed)
    if families is None:
        chosen = {FAMILIES[rng.randrange(len(FAMILIES))]}
        if chosen & {"upgrade", "evacuation"} and rng.random() < 0.5:
            chosen.add(rng.choice(("upgrade", "evacuation")))
    else:
        chosen = set(families)
        unknown = chosen - set(FAMILIES)
        if unknown:
            raise ValueError(f"unknown families: {sorted(unknown)}")
    if "federation" in chosen:
        # exclusive family: the multi-region lab drives region faults
        # and postures only — single-server fault kinds don't compose
        chosen = {"federation"}
    wave_mode, converge_mode = _pick_modes(rng)
    nodes = rng.choice((8, 10, 12, 16))
    pools = rng.choice((2, 4)) if nodes >= 8 else 1
    doc: dict = {
        "version": 1,
        "name": f"gen-{seed}",
        "nodes": nodes,
        "pools": pools,
        "chips_per_node": rng.choice((1, 2)),
        "initial_mode": "off",
        "workers": 4,
        "qps": 0,
        "evidence": False,
        "watch_timeout_s": 2,
        "converge": {"mode": converge_mode, "timeout_s": _TIMEOUT_S},
    }
    actions: List[dict] = []
    controllers: dict = {}

    if "federation" in chosen:
        # schema-2 multi-region episode (ISSUE 16): two regions, ONE
        # posture with per-region windows, plus either a region fault
        # racing the rollout or the region-scoped revoked-root drill
        nodes = rng.choice((8, 12, 16))
        half = nodes // 2
        doc.update({
            "schema": 2,
            "nodes": nodes,
            "pools": 2,
            "regions": [
                {"name": "region-a", "nodes": half, "pools": 1},
                {"name": "region-b", "nodes": nodes - half, "pools": 1},
            ],
        })
        controllers["fleet"] = True
        if rng.random() < 0.4:
            # region latch drill: converge first (the fault waits for
            # THAT region's fleet scans to verify a quote), then pull
            # ONE region's trust root — the oracle pins the non-spill
            doc["evidence"] = True
            doc["attestation"] = True
            actions.append({"at": 0.2, "action": "set_mode",
                            "mode": converge_mode})
            actions.append({"at": 2.0, "action": "fault",
                            "fault": "root_revoked",
                            "region": "region-a"})
        else:
            actions.append({
                "at": 0.2, "action": "set_mode", "mode": converge_mode,
                "windows": {"region-a": 0,
                            "region-b": rng.choice((0.3, 0.6))},
            })
            fault = rng.choice((
                {"fault": "region_partition", "region": "region-b",
                 "duration_s": rng.choice((0.5, 1.0))},
                {"fault": "region_blackout", "region": "region-b",
                 "duration_s": rng.choice((0.5, 1.0))},
                {"fault": "region_latency_skew", "region": "region-b",
                 "delay_s": 0.05,
                 "duration_s": rng.choice((0.5, 1.0))},
                {"fault": "region_evacuate", "region": "region-a"},
            ))
            fault.update({"at": round(rng.uniform(0.3, 0.7), 2),
                          "action": "fault"})
            actions.append(fault)
    elif "attestation" in chosen:
        doc["evidence"] = True
        doc["attestation"] = True
        controllers["fleet"] = True
        if rng.random() < 0.5:
            # rotation drill: wave, rotate mid-scan, converge wave —
            # every node must re-quote under the new primary
            actions.append({"at": 0.2, "action": "set_mode",
                            "mode": wave_mode})
            actions.append({"at": 1.0, "action": "fault",
                            "fault": "key_rotation"})
            actions.append({"at": 1.3, "action": "set_mode",
                            "mode": converge_mode})
        else:
            # revoked-root drill: converge first (the fault itself
            # waits for a VERIFIED fleet scan before revoking), then
            # pull the trust root; forge the node-root document half
            # the time
            actions.append({"at": 0.2, "action": "set_mode",
                            "mode": converge_mode})
            revoke = {"at": 2.0, "action": "fault",
                      "fault": "root_revoked"}
            if rng.random() < 0.5:
                revoke["forge"] = True
            actions.append(revoke)
    elif "policy" in chosen:
        controllers["policy"] = True
        actions.append({
            "at": 0.3, "action": "fault", "fault": "policy_conflict",
            "mode": converge_mode,
            "rival_mode": rng.choice(
                [m for m in _MODES if m != converge_mode]),
            "pool": rng.randrange(pools),
        })
    elif "shards" in chosen:
        controllers["fleet"] = True
        controllers["shards"] = 2
        actions.append({"at": 0.2, "action": "set_mode",
                        "mode": converge_mode})
        actions.append({"at": 0.5, "action": "fault",
                        "fault": "shard_kill", "host": rng.randrange(2)})
    else:
        actions.append({"at": 0.2, "action": "set_mode",
                        "mode": wave_mode})
        actions.append({"at": rng.choice((0.4, 0.6)),
                        "action": "set_mode", "mode": converge_mode})

    if "upgrade" in chosen:
        actions.append({
            "at": round(rng.uniform(0.2, 0.7), 2),
            "action": "fault", "fault": "agent_upgrade",
            "cohorts": rng.choice((2, 3)),
            "stagger_s": rng.choice((0.2, 0.4)),
        })
    if "evacuation" in chosen:
        actions.append({
            "at": round(rng.uniform(0.2, 0.5), 2),
            "action": "fault", "fault": "evacuation_drain",
            "count": max(1, nodes // 3),
            "duration_s": rng.choice((0.8, 1.5)),
        })
    if not chosen & {"attestation", "policy", "federation"}:
        actions.extend(_infra_extras(rng, nodes))

    if controllers:
        doc["controllers"] = controllers
    doc["actions"] = sorted(actions, key=lambda a: a.get("at", 0.0))
    validate_scenario(doc)  # the generator must only emit valid docs
    return doc


# ------------------------------------------------------------ episodes
@dataclasses.dataclass
class EpisodeResult:
    doc: dict
    artifact: dict
    violations: List[Violation]
    #: the live lab (post-run, torn down) for deeper inspection; not
    #: serialized into reports
    lab: object = dataclasses.field(repr=False, default=None)

    @property
    def ok(self) -> bool:
        return not self.violations


def run_episode(doc: dict, *,
                sample_interval_s: float = 0.1) -> EpisodeResult:
    """Run one scenario document through the live harness and the
    oracle. A background probe samples shard-leadership uniqueness
    during the run (post-hoc state can't see a transient split brain);
    fleet scans are accelerated (TPU_CC_FLEET_MIN_SCAN_GAP_S) so the
    attestation latch arms inside episode time."""
    from tpu_cc_manager.simlab.federation import FederationLab
    from tpu_cc_manager.simlab.runner import SimLab

    sc = validate_scenario(doc)
    prior_gap = os.environ.get("TPU_CC_FLEET_MIN_SCAN_GAP_S")
    os.environ["TPU_CC_FLEET_MIN_SCAN_GAP_S"] = "0.5"
    # schema-2 regions episodes run the multi-region lab (its artifact
    # carries the metrics.federation block the region invariants read)
    lab = FederationLab(sc) if sc.regions else SimLab(sc)
    stop = threading.Event()
    probe_hits: List[Violation] = []

    def probe() -> None:
        while not stop.is_set():
            v = sample_shard_leadership(
                getattr(lab, "shard_manager", None))
            if v is not None and not probe_hits:
                probe_hits.append(dataclasses.replace(
                    v, detail=v.detail + " (observed live, mid-run)"))
            stop.wait(sample_interval_s)

    thread = threading.Thread(target=probe, daemon=True,
                              name="propgen-leader-probe")
    thread.start()
    try:
        artifact = lab.run()
    finally:
        stop.set()
        thread.join(timeout=2)
        if prior_gap is None:
            os.environ.pop("TPU_CC_FLEET_MIN_SCAN_GAP_S", None)
        else:
            os.environ["TPU_CC_FLEET_MIN_SCAN_GAP_S"] = prior_gap
    violations = check_run(lab, artifact, extra=probe_hits)
    return EpisodeResult(doc=doc, artifact=artifact,
                         violations=violations, lab=lab)


# ------------------------------------------------------------ shrinking
def _drives_convergence(action: dict, converge_mode: str) -> bool:
    """Does this action initiate the fleet's change to the converge
    mode? (A set_mode wave, a policy, or the conflict fault's OWNER
    policy targeting it.)"""
    if action.get("action") in ("set_mode", "create_policy"):
        return action.get("mode") == converge_mode
    if (action.get("action") == "fault"
            and action.get("fault") == "policy_conflict"):
        return action.get("mode") == converge_mode
    return False


def shrink(doc: dict, reproduces: Callable[[dict], bool], *,
           seed: int = 0, max_runs: int = 32) -> Tuple[dict, int]:
    """Greedy delta-shrink of a violating episode: repeatedly try
    (a) DROPPING one action, then (b) REORDERING one action to the
    front of the timeline (``at`` → 0.0), keeping an edit only when
    ``reproduces(candidate)`` says the violation still fires.
    Candidates that fail schema validation are skipped (never
    counted); ``max_runs`` bounds the reproduction runs, since each
    may be a live fleet. Deterministic for a given ``seed``: the probe
    order is seeded, so the same find shrinks the same way twice.

    One structural rule on top of schema validity: if the ORIGINAL
    episode contains an action that initiates the converge-mode change
    (a set_mode wave / policy targeting converge.mode), every
    candidate must retain one. Dropping it would make ANY
    convergence-invariant find "reproduce" trivially — a fleet never
    told to converge proves nothing about the bug being shrunk.

    Returns (shrunk doc, reproduction runs spent). The shrunk doc is
    minimal w.r.t. single-action drops within the run budget — ddmin's
    1-minimality, the QuickCheck-style contract tests pin."""
    rng = _rng("shrink", seed)
    current = dict(doc)
    runs = 0
    converge_mode = (doc.get("converge") or {}).get("mode")
    must_keep_driver = converge_mode is not None and any(
        _drives_convergence(a, converge_mode) for a in doc["actions"]
    )

    def attempt(cand: dict) -> bool:
        nonlocal runs
        if runs >= max_runs:
            return False
        if must_keep_driver and not any(
                _drives_convergence(a, converge_mode)
                for a in cand["actions"]):
            return False  # structural, not a spent run
        try:
            validate_scenario(cand)
        except ScenarioError:
            return False
        runs += 1
        return bool(reproduces(cand))

    improved = True
    while improved and runs < max_runs:
        improved = False
        actions = current["actions"]
        order = list(range(len(actions)))
        rng.shuffle(order)
        # drop pass: fewer events beats everything else
        for i in order:
            if len(current["actions"]) <= 1:
                break
            cand = dict(current)
            cand["actions"] = (current["actions"][:i]
                               + current["actions"][i + 1:])
            if attempt(cand):
                current = cand
                improved = True
                break
        if improved:
            continue
        # reorder pass: pull one event to the front — "does the storm
        # need to arrive mid-flight, or does it break even first?"
        for i in order:
            acts = current["actions"]
            if i >= len(acts) or acts[i].get("at", 0.0) == 0.0:
                continue
            moved = dict(acts[i])
            moved["at"] = 0.0
            cand = dict(current)
            cand["actions"] = sorted(
                acts[:i] + [moved] + acts[i + 1:],
                key=lambda a: a.get("at", 0.0),
            )
            if cand["actions"] == acts:
                continue
            if attempt(cand):
                current = cand
                improved = True
                break
    return current, runs


def reproduces_violation(invariant: str) -> Callable[[dict], bool]:
    """A live reproduction predicate for :func:`shrink`: re-run the
    candidate episode and ask whether the SAME invariant still
    breaks (a shrink step that trades one violation for a different
    one is not a simplification of the find). The returned callable
    keeps the last REPRODUCING run as ``.last_result`` — that run
    belongs to the accepted (shrunk) document, so dump_find can pair
    the shrunk scenario with ITS OWN artifact and violations instead
    of the pre-shrink episode's."""

    def check(cand: dict) -> bool:
        try:
            result = run_episode(cand)
        except Exception:
            log.warning("shrink re-run crashed; treating as "
                        "non-reproducing", exc_info=True)
            return False
        hit = any(v.invariant == invariant for v in result.violations)
        if hit:
            check.last_result = result
        return hit

    check.last_result = None
    return check


# -------------------------------------------------------------- output
def dump_find(doc: dict, violations: Sequence[Violation],
              artifact: Optional[dict] = None, *,
              scenario_dir: str = "scenarios",
              report_dir: str = "propgen-finds",
              original_doc: Optional[dict] = None
              ) -> Tuple[str, str]:
    """Persist one find: the (possibly shrunk) episode as a REPLAYABLE
    canonical ``scenarios/gen-*.json`` — a first-class scenario file,
    promotable to a named scenario by committing it — plus a report
    sidecar (separate directory: everything under ``scenario_dir``
    must BE a scenario) carrying the violations, the stitched
    flight-recorder timeline, and the pre-shrink original."""
    name = doc.get("name") or "gen-unnamed"
    if not name.startswith("gen-"):
        name = f"gen-{name}"
    os.makedirs(scenario_dir, exist_ok=True)
    os.makedirs(report_dir, exist_ok=True)
    scenario_path = os.path.join(scenario_dir, f"{name}.json")
    with open(scenario_path, "w") as f:
        f.write(canonical_scenario_text(doc))
    report = {
        "scenario": name,
        "scenario_path": scenario_path,
        "violations": [v.to_dict() for v in violations],
        "invariants_checked": True,
    }
    if artifact is not None:
        report["artifact"] = artifact
        stitch = (artifact.get("metrics") or {}).get("trace_stitch")
        if stitch is not None:
            # the cross-process story of the failing run, stitched by
            # trace id (flightrec.stitch_by_trace) — the first thing a
            # triager reads
            report["timeline"] = stitch.get("timeline_example")
    if original_doc is not None and original_doc != doc:
        report["original_scenario"] = original_doc
    report_path = os.path.join(report_dir, f"{name}.report.json")
    with open(report_path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    return scenario_path, report_path


# -------------------------------------------------------------- driver
def explore(seeds: Sequence[int], *,
            families: Optional[Iterable[str]] = None,
            shrink_finds: bool = True,
            max_shrink_runs: int = 8,
            scenario_dir: str = "scenarios",
            report_dir: str = "propgen-finds",
            log_fn: Callable[[str], None] = print) -> List[dict]:
    """Run one episode per seed; shrink and dump every find. Returns
    one summary dict per seed ({seed, name, ok, violations,
    scenario_path?, report_path?, convergence})."""
    from tpu_cc_manager.simlab.report import convergence_key

    summaries: List[dict] = []
    for seed in seeds:
        doc = generate_episode(seed, families=families)
        log_fn(f"propgen: seed {seed} — {doc['name']} "
               f"({doc['nodes']} nodes, {len(doc['actions'])} actions)")
        result = run_episode(doc)
        summary: dict = {
            "seed": seed,
            "name": doc["name"],
            "ok": result.ok,
            "violations": [v.to_dict() for v in result.violations],
            "convergence": (result.artifact.get("metrics") or {}).get(
                convergence_key(doc["nodes"])),
        }
        if not result.ok:
            log_fn(f"propgen: seed {seed} VIOLATED: "
                   + "; ".join(f"{v.invariant}: {v.detail}"
                               for v in result.violations[:3]))
            shrunk, spent = doc, 0
            dump_result = result
            if shrink_finds and max_shrink_runs > 0:
                target = result.violations[0].invariant
                repro = reproduces_violation(target)
                shrunk, spent = shrink(
                    doc, repro, seed=seed, max_runs=max_shrink_runs,
                )
                if shrunk != doc and repro.last_result is not None:
                    # the report must describe the SHRUNK episode's own
                    # run — timeline and violations from the pre-shrink
                    # run would reference actions the persisted
                    # scenario no longer contains
                    dump_result = repro.last_result
                log_fn(f"propgen: shrink kept "
                       f"{len(shrunk['actions'])}/"
                       f"{len(doc['actions'])} actions "
                       f"({spent} re-runs)")
            spath, rpath = dump_find(
                shrunk, dump_result.violations, dump_result.artifact,
                scenario_dir=scenario_dir, report_dir=report_dir,
                original_doc=doc,
            )
            summary.update(scenario_path=spath, report_path=rpath,
                           shrink_runs=spent)
            log_fn(f"propgen: find persisted — replay with "
                   f"`python -m tpu_cc_manager simlab run {spath}`")
        summaries.append(summary)
    return summaries


def main_from_args(args) -> int:
    """CLI dispatch for ``simlab propgen`` (called via
    tpu_cc_manager.simlab.main_from_args)."""
    try:
        seeds = [int(s) for s in str(args.seeds).split(",") if s != ""]
    except ValueError:
        print(f"propgen: --seeds must be a comma-separated int list, "
              f"got {args.seeds!r}")
        return 2
    if not seeds:
        print("propgen: no seeds given")
        return 2
    families = None
    if args.families:
        families = [f for f in args.families.split(",") if f]
        unknown = sorted(set(families) - set(FAMILIES))
        if unknown:
            print(f"propgen: unknown families {unknown}; known: "
                  f"{sorted(FAMILIES)}")
            return 2
    summaries = explore(
        seeds,
        families=families,
        shrink_finds=not args.no_shrink,
        max_shrink_runs=args.max_shrink_runs,
        scenario_dir=args.scenario_dir,
        report_dir=args.report_dir,
    )
    print(json.dumps(summaries, indent=2, sort_keys=True))
    return 0 if all(s["ok"] for s in summaries) else 1
