"""Replica shell + worker pool — live agents without thread-per-node.

Each :class:`ReplicaShell` is one simulated node's agent: a real
:class:`~tpu_cc_manager.engine.ModeEngine` over its own fake device
backend, publishing the observed-state label (and optionally evidence)
through a SHARED flow-controlled HTTP client. What it deliberately does
NOT own is threads: desired-mode changes land in a last-value mailbox
(fed by the shared watch pump) and a bounded :class:`WorkerPool`
executes the reconciles — the coalescing contract is the agent's
(``SyncableModeConfig`` semantics: N rapid flips collapse to the newest
value), the execution model is what lets 256 replicas fit a 1-core
sandbox.

Failure semantics mirror the real agent (agent.py reconcile): invalid
modes reject cleanly with a ``failed`` state label; retryable failures
re-enter the queue after a short delay (the self-repair analog) so a
replica that lost a state-label write to a 429 storm still converges.

Shared-loop mode (ISSUE 13, ``TPU_CC_SIMLAB_SHARED_LOOP=1``): the
``kube`` every shell publishes through may be ONE
:class:`~tpu_cc_manager.k8s.aio_bridge.SyncKubeFacade` — the whole
fleet's writes then multiplex a single event loop's pipelined
connection pool (k8s/aio.py) instead of checking thread-private
sockets out of the threaded client. The shell is agnostic by design:
both clients speak the same ``KubeClient``/throttle surface, so the
runner swaps the transport without a scenario byte changing
(docs/io.md §"The async core"; the artifact's ``metrics.kube_io``
records which core served the run).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Dict, List, Optional

from tpu_cc_manager import labels as L
from tpu_cc_manager.device.gate import DeviceGate
from tpu_cc_manager.engine import FatalModeError, ModeEngine, NullDrainer
from tpu_cc_manager.flightrec import FlightRecorder
from tpu_cc_manager.k8s.batch import NodePatchBatcher
from tpu_cc_manager.modes import STATE_FAILED, InvalidModeError

log = logging.getLogger("tpu-cc-manager.simlab.replica")

#: mailbox sentinel: "no pending desired value"
_EMPTY = object()

#: worker-queue sentinel telling a worker thread to exit
_STOP = object()


class SimGate(DeviceGate):
    """In-memory device gate: records the permission bits chmod WOULD
    set on each device path instead of touching a devfs that fake
    chips don't have. This makes the engine's fail-secure contract —
    a device locked for a flip stays at FLIP_LOCK_PERMS until a later
    successful verify reopens it — OBSERVABLE per replica, which is
    exactly what the lifecycle invariants oracle
    (simlab.invariants) checks at quiescence."""

    def __init__(self) -> None:
        super().__init__(enabled=True)
        self._perms_lock = threading.Lock()
        self._perms: Dict[str, int] = {}

    def _chmod(self, path: str, perms: int, *, must_succeed: bool) -> bool:
        with self._perms_lock:
            self._perms[path] = perms
        return True

    def current_perms(self, path: str):
        with self._perms_lock:
            return self._perms.get(path)

    def perms_snapshot(self) -> Dict[str, int]:
        with self._perms_lock:
            return dict(self._perms)


class ReplicaShell:
    """One node's reconciling agent, mailbox-driven."""

    #: retryable-failure requeue delay and lifetime retry budget (the
    #: agent's REPAIR_INTERVAL_S analog, scaled to scenario time; the
    #: convergence timeout is the real backstop)
    REPAIR_DELAY_S = 0.5
    MAX_REPAIRS = 50

    def __init__(
        self,
        node_name: str,
        kube,
        backend,
        tracer,
        *,
        evidence: bool = False,
        metrics=None,
        attestor=None,
    ):
        self.node_name = node_name
        self.kube = kube
        self.backend = backend
        self.evidence = evidence
        #: optional per-replica attest.FakeTpm (scenario.attestation):
        #: the engine extends ITS measured flip history and evidence
        #: quotes come from IT, so one process carries a fleet of
        #: independent PCRs (runner.AttestationLab owns the state dirs
        #: and the verifier-side trust root)
        self.attestor = attestor
        #: recording device gate: the oracle's fail-secure probe
        self.gate = SimGate()
        #: optional obs.Metrics — the SAME metric set a real agent
        #: exposes, so this replica is a genuine scrape target for the
        #: fleet observatory (fleetobs.py, ISSUE 9): outcomes, the
        #: reconcile-duration histogram, and the batcher's publish-loss
        #: counters all land here exactly as agent.py wires them
        self.metrics = metrics
        # the write-coalescing layer (k8s.batch): the state-label write
        # is the replica's carrier — it transports the PREVIOUS
        # this replica's flight recording (ISSUE 8): small rings — the
        # runner collects every replica's snapshot after the run and
        # stitches them fleet-wide by trace id. The shared tracer can't
        # be sinked per replica, so the reconcile root spans are
        # recorded explicitly in _reconcile.
        self.recorder = FlightRecorder(
            name=node_name, span_ring=64, event_ring=64, sample_ring=32,
        )
        # reconcile's deferred evidence, so a flip costs one write, not
        # two. The runner's settle pass flushes stragglers. Publish-loss
        # events note into THIS replica's recorder (not the process
        # default), so a write-storm's retried/dropped keys reach the
        # collected recordings.
        if metrics is not None:
            self.batcher = NodePatchBatcher(
                kube, node_name, recorder=self.recorder,
                on_coalesced=(
                    lambda kind: metrics
                    .publications_coalesced_total.inc(kind)
                ),
                on_retry=lambda kind: metrics.publish_retries_total.inc(),
                on_drop=(
                    lambda kind: metrics
                    .publications_dropped_total.inc(kind)
                ),
            )
        else:
            self.batcher = NodePatchBatcher(kube, node_name,
                                            recorder=self.recorder)
        self.engine = ModeEngine(
            set_state_label=self.batcher.write_state_label,
            drainer=NullDrainer(),
            evict_components=False,
            backend=backend,
            tracer=tracer,
            recorder=self.recorder,
            gate=self.gate,
            attestor=attestor,
        )
        self._tracer = tracer
        self._lock = threading.Lock()
        self._pending = _EMPTY
        self._pending_trace: Optional[str] = None
        self._pending_lag: Optional[float] = None
        self._queued = False
        self.alive = True
        self.applied: Optional[str] = None
        #: code-version behavior tag (the rolling-upgrade drill):
        #: "v1" is the baseline; an upgraded replica advertises its
        #: version as the cc.agent-version annotation, deferred
        #: through the batcher so it rides the next carrier write —
        #: the observable behavior difference between the two code
        #: versions reconciling one pool mid-rollout. Written under
        #: _lock (upgrade()), read on the worker thread.
        self.version = "v1"
        self._version_published = "v1"
        # counters (read single-threaded at report time)
        self.reconciles = 0
        self.outcomes: Dict[str, int] = {}
        self.repairs = 0
        self.coalesced = 0
        self._resubmit: Optional[
            Callable[[str, str, Optional[str]], None]] = None
        self._timers: List[threading.Timer] = []
        #: evidence generation bookkeeping (the agent's
        #: _evidence_published_gen analog, scaled down): wanted >
        #: published means the newest document hasn't landed and the
        #: next success or settle flush must deliver it
        self.evidence_wanted_gen = 0
        self.evidence_published_gen = 0

    # ------------------------------------------------------------ mailbox
    def offer(self, value: str, trace: Optional[str] = None,
              lag: Optional[float] = None) -> bool:
        """Last-value-wins mailbox write. Returns True when the caller
        should enqueue this replica on the worker queue (not already
        queued, and alive — a crashed replica keeps the pending value
        for its restart to pick up). ``trace``/``lag`` ride the value
        (and coalesce with it — the newest desired write's trace owns
        the reconcile, exactly the real agent's contract)."""
        with self._lock:
            if self._pending is not _EMPTY and self._pending != value:
                self.coalesced += 1  # overwritten unread value
            self._pending = value
            self._pending_trace = trace
            self._pending_lag = lag
            if self._queued or not self.alive:
                return False
            self._queued = True
            return True

    def run_pending(self) -> None:
        """Worker entry point: drain the mailbox, reconciling the newest
        desired value each pass, until nothing is pending."""
        while True:
            with self._lock:
                if self._pending is _EMPTY or not self.alive:
                    self._queued = False
                    break
                value = self._pending
                trace, lag = self._pending_trace, self._pending_lag
                self._pending = _EMPTY
                self._pending_trace = self._pending_lag = None
            self._reconcile(value, trace, lag)
        # mailbox drained: flush any deferred publication that found no
        # carrier write (respects the batcher's flush window/backoff) —
        # the replica's idle-tick analog
        self.batcher.maybe_flush()

    # ---------------------------------------------------------- reconcile
    def _reconcile(self, mode: str, trace: Optional[str] = None,
                   lag: Optional[float] = None) -> None:
        outcome = "error"
        ok = False
        # adopt the desired-writer's trace context (simlab driver or
        # policy-driven rollout): this replica's reconcile tree joins
        # the fleet-wide trace the runner stitches by trace id
        with self._tracer.adopt_remote(trace):
            with self._tracer.span(
                "reconcile", mode=mode, node=self.node_name
            ) as root:
                if lag is not None:
                    # the pump-lag measurement lands on the span it
                    # belongs to, not only a disembodied histogram
                    root.attrs["pump_lag_s"] = round(lag, 6)
                try:
                    ok = self.engine.set_mode(mode)
                    outcome = "success" if ok else "failure"
                except InvalidModeError as e:
                    log.error("%s: rejecting desired mode: %s",
                              self.node_name, e)
                    self._publish_failed()
                    outcome = "invalid"
                except FatalModeError as e:
                    # the DaemonSet-restart analog: this replica is down
                    # until a scripted restart brings it back
                    log.error("%s: fatal: %s", self.node_name, e)
                    with self._lock:
                        self.alive = False
                    outcome = "fatal"
                except Exception:
                    log.exception("%s: reconcile crashed", self.node_name)
                    self._publish_failed()
                root.attrs["outcome"] = outcome
        # the root span is closed (dur_s final) — record it in this
        # replica's black box for the runner's fleet-timeline stitch
        self.recorder.observe_span(root)
        self.recorder.note("reconcile", mode=mode, outcome=outcome)
        self.reconciles += 1
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        if self.metrics is not None:
            self.metrics.reconciles_total.inc(outcome)
            # the (possibly adopted) reconcile trace id rides as the
            # latency bucket's exemplar (ISSUE 15): a slow bucket in
            # this replica's exposition names a trace the fleet-wide
            # stitch resolves back to the desired write that caused it
            self.metrics.reconcile_duration.observe(
                root.dur_s, trace_id=root.trace_id)
        if ok:
            self.applied = mode
            if self.evidence:
                self._defer_evidence()
            with self._lock:
                version = self.version
                publish_version = version != self._version_published
            if publish_version:
                self._defer_version(version)
        elif outcome in ("failure", "error"):
            self._arm_repair(mode, trace)

    def _defer_evidence(self) -> None:
        """Build this node's evidence document and hand it to the
        coalescing batcher: it rides the NEXT reconcile's state write
        (or the runner's settle flush); only the newest generation is
        ever sent, superseded ones are counted by the batcher."""
        import json as _json

        from tpu_cc_manager.evidence import build_evidence

        try:
            doc = build_evidence(
                self.node_name, self.backend,
                attestor=(self.attestor if self.attestor is not None
                          else "auto"),
            )
            payload = _json.dumps(doc, sort_keys=True,
                                  separators=(",", ":"))
        except Exception:
            log.warning("%s: evidence build failed", self.node_name,
                        exc_info=True)
            return
        self.evidence_wanted_gen += 1

        def landed(gen: int) -> None:
            self.evidence_published_gen = max(
                self.evidence_published_gen, gen
            )

        self.batcher.defer(
            "evidence",
            annotations={L.EVIDENCE_ANNOTATION: payload},
            gen=self.evidence_wanted_gen,
            on_published=landed,
        )

    def _defer_version(self, version: str) -> None:
        """Advertise the running code version (upgrade drill): a
        coalescing publication riding the next carrier write — an
        upgrade costs zero extra round trips, pinned by the oracle's
        writes-per-flip budget."""

        def landed(gen: int) -> None:
            with self._lock:
                self._version_published = version

        self.batcher.defer(
            "agent_version",
            annotations={L.AGENT_VERSION_ANNOTATION: version},
            on_published=landed,
        )

    def _publish_failed(self) -> None:
        try:
            self.batcher.write_state_label(STATE_FAILED)
        except Exception:
            log.warning("%s: could not publish failed state",
                        self.node_name)

    def _arm_repair(self, mode: str, trace: Optional[str] = None) -> None:
        """Requeue a retryable failure after a short delay, like the
        agent's idle-tick self-repair — a label event will never come
        to retry it (the desired label is already correct). The failed
        round's trace context rides the retry: the repair is still
        part of the same desired-write's story."""
        if self._resubmit is None or self.repairs >= self.MAX_REPAIRS:
            return
        self.repairs += 1

        def fire():
            with self._lock:
                if not self.alive or self._pending is not _EMPTY:
                    return  # newer work already queued
            self._resubmit(self.node_name, mode, trace)

        t = threading.Timer(self.REPAIR_DELAY_S, fire)
        t.daemon = True
        t.start()
        self._timers.append(t)

    # ------------------------------------------------------------- faults
    def crash(self) -> None:
        with self._lock:
            self.alive = False

    def restart(self) -> None:
        """Back alive; the caller re-reads the node's desired label and
        resubmits (a restarted agent's prime-read analog)."""
        with self._lock:
            self.alive = True

    def upgrade(self, version: str) -> None:
        """Process-replacement half of a rolling agent upgrade: down,
        new code version swapped in. The injector restarts it with the
        same prime-read the crash fault uses; the first successful
        reconcile after restart advertises the new version."""
        with self._lock:
            self.alive = False
            self.version = version

    def close(self) -> None:
        for t in self._timers:
            t.cancel()
        self._timers.clear()


class WorkerPool:
    """Bounded reconcile executor: N daemon workers over one queue of
    replica names. ``submit`` is the only producer API — it routes
    through the replica mailbox so concurrent producers (pump, fault
    restarts, repair timers) keep last-value-wins semantics."""

    def __init__(self, replicas: Dict[str, ReplicaShell], n_workers: int):
        self.replicas = replicas
        self._q: "queue.Queue" = queue.Queue()
        self._threads: List[threading.Thread] = []
        self.n_workers = n_workers
        for r in replicas.values():
            r._resubmit = self.submit

    def start(self) -> "WorkerPool":
        for i in range(self.n_workers):
            t = threading.Thread(
                target=self._worker, name=f"simlab-worker-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        return self

    def submit(self, name: str, value: str,
               trace: Optional[str] = None,
               lag: Optional[float] = None) -> None:
        replica = self.replicas.get(name)
        if replica is None:
            return
        if replica.offer(value, trace, lag):
            self._q.put(name)

    def requeue(self, name: str) -> None:
        """Enqueue a replica whose mailbox already holds a pending value
        (restart after crash)."""
        replica = self.replicas.get(name)
        if replica is None:
            return
        with replica._lock:
            if (replica._pending is _EMPTY or replica._queued
                    or not replica.alive):
                return
            replica._queued = True
        self._q.put(name)

    def _worker(self) -> None:
        while True:
            name = self._q.get()
            if name is _STOP:
                return
            try:
                self.replicas[name].run_pending()
            except Exception:
                log.exception("worker failed on %s", name)

    def stop(self) -> None:
        for _ in self._threads:
            self._q.put(_STOP)
        for t in self._threads:
            t.join(timeout=5)
        for r in self.replicas.values():
            r.close()
