"""Scripted fault injection for simlab scenarios.

Each injector method executes one fault action from the timeline and
returns a log entry for the artifact. Faults act on the same surfaces
production faults would: the FakeKube store's injection knobs
(watch/list failures — the wire clients observe them as real HTTP
errors), the shared data-plane client's token bucket (throttle
squeeze), replica liveness (crash/restart), and the coordination Lease
(leader flap — stolen exactly as a rogue writer would steal it, via a
CAS replace)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.client import ApiException, ConflictError

log = logging.getLogger("tpu-cc-manager.simlab.faults")


class FaultInjector:
    def __init__(
        self,
        *,
        store,
        replicas: Dict[str, object],
        pool,
        data_kube,
        ops_kube,
        base_qps: float,
        lease_names: List[str],
        lease_namespace: str = "tpu-system",
        shard_manager=None,
    ):
        self.store = store
        self.replicas = replicas
        self.pool = pool
        self.data_kube = data_kube
        self.ops_kube = ops_kube
        self.base_qps = base_qps
        self.lease_names = lease_names
        self.lease_namespace = lease_namespace
        #: tpu_cc_manager.shard.ShardManager when the scenario runs a
        #: sharded control plane (controllers.shards > 0)
        self.shard_manager = shard_manager
        self._timers: List[threading.Timer] = []
        self.crashed_total = 0
        self.restarted_total = 0
        #: monotonic stamp of the most recent shard_kill — the runner
        #: derives shard_failover_convergence_s (kill -> fleet
        #: converged) from it
        self.last_shard_kill_t: float = 0.0

    # ------------------------------------------------------------ dispatch
    def inject(self, fault: str, params: dict, rel_t: float) -> dict:
        entry = {"at_s": round(rel_t, 3), "fault": fault}
        entry.update(getattr(self, f"_{fault}")(params))
        log.info("fault injected: %s", entry)
        return entry

    def _timer(self, delay_s: float, fn) -> None:
        t = threading.Timer(delay_s, fn)
        t.daemon = True
        t.start()
        self._timers.append(t)

    # -------------------------------------------------------------- kinds
    def _agent_crash(self, params: dict) -> dict:
        count = min(int(params["count"]), len(self.replicas))
        restart_after_s = float(params.get("restart_after_s", 1.0))
        victims = [
            name for name, r in sorted(self.replicas.items())
            if r.alive
        ][:count]
        for name in victims:
            self.replicas[name].crash()
        self.crashed_total += len(victims)

        def restart():
            for name in victims:
                replica = self.replicas[name]
                replica.restart()
                self.restarted_total += 1
                # the restarted agent's prime read: desired comes from
                # the cluster, not from anything the dead process held.
                # The cc.trace annotation rides the same node object
                # (ISSUE 8), so a post-crash reconcile still joins the
                # desired write's fleet-wide trace — exactly what the
                # real agent's NodeWatcher.prime + latest_trace_context
                # does after a DaemonSet restart.
                try:
                    node = self.ops_kube.get_node(name)
                    meta = node["metadata"]
                    desired = (meta.get("labels") or {}).get(
                        L.CC_MODE_LABEL
                    )
                    trace = (meta.get("annotations") or {}).get(
                        L.CC_TRACE_ANNOTATION
                    )
                except ApiException:
                    desired = None
                    trace = None
                if desired is not None:
                    self.pool.submit(name, desired, trace=trace)
                else:
                    self.pool.requeue(name)  # drain anything it missed

        self._timer(restart_after_s, restart)
        return {"crashed": len(victims),
                "restart_after_s": restart_after_s}

    def _watch_drop(self, params: dict) -> dict:
        count = int(params["count"])
        with self.store._lock:
            self.store.fail_next_watches += count
        return {"count": count}

    def _watch_410(self, params: dict) -> dict:
        self.store.compact_watch_history()
        return {}

    def _list_429(self, params: dict) -> dict:
        count = int(params["count"])
        with self.store._lock:
            self.store.fail_next_lists += count
        return {"count": count}

    def _write_429(self, params: dict) -> dict:
        count = int(params["count"])
        with self.store._lock:
            self.store.fail_next_node_writes += count
        return {"count": count}

    def _throttle_squeeze(self, params: dict) -> dict:
        qps = float(params["qps"])
        duration_s = float(params["duration_s"])
        self.data_kube.set_qps(qps)
        self._timer(
            duration_s, lambda: self.data_kube.set_qps(self.base_qps)
        )
        return {"qps": qps, "duration_s": duration_s}

    def _leader_flap(self, params: dict) -> dict:
        """Steal every election Lease for one term: the holder demotes
        at its next renew, the thief never renews, and a live replica
        re-acquires after staleness — adoption of any in-flight rollout
        record included."""
        from tpu_cc_manager.leader import _now_rfc3339

        stolen = []
        for name in self.lease_names:
            for _ in range(5):  # CAS retry against a racing renew
                try:
                    lease = self.ops_kube.get_lease(
                        self.lease_namespace, name
                    )
                except ApiException:
                    break  # no lease yet: nothing to steal
                spec = lease.setdefault("spec", {})
                spec["holderIdentity"] = "simlab-flap"
                spec["renewTime"] = _now_rfc3339()
                try:
                    self.ops_kube.replace_lease(
                        self.lease_namespace, name, lease
                    )
                    stolen.append(name)
                    break
                except (ConflictError, ApiException):
                    time.sleep(0.02)
        return {"leases_stolen": stolen}

    def _shard_kill(self, params: dict) -> dict:
        """Crash one controller shard host mid-run: its partition's
        lease goes stale (no release) and a surviving host must
        re-acquire it and resume the partition's controllers — the
        failover drill the shard_failover_convergence_s axis times."""
        if self.shard_manager is None:
            return {"skipped": "no shard manager"}
        host = int(params.get("host", 0))
        self.last_shard_kill_t = time.monotonic()
        entry = self.shard_manager.kill_host(host)
        restart_after_s = params.get("restart_after_s")
        if restart_after_s is not None:
            self._timer(
                float(restart_after_s),
                lambda: self.shard_manager.restart_host(host),
            )
            entry["restart_after_s"] = float(restart_after_s)
        return entry

    # ----------------------------------------------------------- teardown
    def cancel(self) -> None:
        """Cancel undelivered timers (teardown; restart timers have
        either fired inside the convergence wait or the run already
        failed)."""
        for t in self._timers:
            t.cancel()
        self._timers.clear()
