"""Scripted fault injection for simlab scenarios.

Each injector method executes one fault action from the timeline and
returns a log entry for the artifact. Faults act on the same surfaces
production faults would: the FakeKube store's injection knobs
(watch/list failures — the wire clients observe them as real HTTP
errors), the shared data-plane client's token bucket (throttle
squeeze), replica liveness (crash/restart), the coordination Lease
(leader flap — stolen exactly as a rogue writer would steal it, via a
CAS replace), and — the lifecycle families (ISSUE 12) — replica code
versions (rolling upgrade), the attestation key material (rotation /
revoked trust root, incl. the node-root forgery drill), the policy
surface (overlapping claims), and node cordons (evacuation drains
racing flips).

Timer discipline: every delayed callback goes through :meth:`_timer`,
which gates execution on the injector's cancelled flag — a timer that
fires after :meth:`cancel` is a no-op instead of mutating a torn-down
replica (the cancel-vs-in-flight-callback race is pinned by
tests/test_simlab.py). Restorative timers (throttle restore, uncordon)
additionally register with :meth:`settle` so a run that converges
before their delay still ends in the restored state.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.client import ApiException, ConflictError

log = logging.getLogger("tpu-cc-manager.simlab.faults")


class FaultInjector:
    def __init__(
        self,
        *,
        store,
        replicas: Dict[str, object],
        pool,
        data_kube,
        ops_kube,
        base_qps: float,
        lease_names: List[str],
        lease_namespace: str = "tpu-system",
        shard_manager=None,
        nodes_in_pool: Optional[Callable[[Optional[int]], List[str]]] = None,
        attest_lab=None,
        create_policy: Optional[Callable[..., dict]] = None,
        attestation_armed: Optional[Callable[[], bool]] = None,
        converge_mode: Optional[str] = None,
    ):
        self.store = store
        self.replicas = replicas
        self.pool = pool
        self.data_kube = data_kube
        self.ops_kube = ops_kube
        self.base_qps = base_qps
        self.lease_names = lease_names
        self.lease_namespace = lease_namespace
        #: tpu_cc_manager.shard.ShardManager when the scenario runs a
        #: sharded control plane (controllers.shards > 0)
        self.shard_manager = shard_manager
        #: pool-scope resolver (runner._nodes_in_pool); None scopes to
        #: every replica
        self.nodes_in_pool = nodes_in_pool
        #: runner.AttestationLab when scenario.attestation is on — the
        #: key_rotation / root_revoked surfaces
        self.attest_lab = attest_lab
        #: runner hook creating one TPUCCPolicy CR (policy_conflict)
        self.create_policy = create_policy
        #: runner hook: has any fleet scan verified a quote yet? The
        #: revoked-root drill waits for the outage latch to ARM before
        #: revoking — revoking a never-verified fleet tests nothing
        self.attestation_armed = attestation_armed
        #: the scenario's converge mode (forgery picks a contradicting
        #: claim deterministically)
        self.converge_mode = converge_mode
        self._timers: List[threading.Timer] = []
        #: guards _timers/_cancelled/_restores: cancel() vs an
        #: in-flight timer callback must never race a torn-down
        #: replica (the satellite fix — callbacks re-check under this
        #: lock before touching anything)
        self._timers_lock = threading.Lock()
        self._cancelled = False
        #: (name, fn) restorative callbacks not yet run: settle() runs
        #: them early so convergence-before-delay still restores state
        self._restores: Dict[int, Callable[[], None]] = {}
        self._restore_seq = 0
        #: restores currently EXECUTING (timer thread or settle);
        #: settle() waits these out — the oracle must never judge a
        #: fleet mid-uncordon
        self._restores_inflight = 0
        self._restores_done = threading.Condition(self._timers_lock)
        self.crashed_total = 0
        self.restarted_total = 0
        self.upgraded_total = 0
        #: logical node-write mutation units this injector's faults
        #: issued through the REAL write path (cordon/uncordon spec
        #: flips) — the invariants oracle subtracts them from the
        #: fleet's writes-per-flip budget
        self.fault_write_units = 0
        #: nodes the evacuation_drain fault cordoned (oracle: none may
        #: stay cordoned at quiescence)
        self.evacuated_nodes: List[str] = []
        #: monotonic stamp of the most recent shard_kill — the runner
        #: derives shard_failover_convergence_s (kill -> fleet
        #: converged) from it
        self.last_shard_kill_t: float = 0.0

    # ------------------------------------------------------------ dispatch
    def inject(self, fault: str, params: dict, rel_t: float) -> dict:
        entry = {"at_s": round(rel_t, 3), "fault": fault}
        entry.update(getattr(self, f"_{fault}")(params))
        log.info("fault injected: %s", entry)
        return entry

    def _timer(self, delay_s: float, fn, restore: bool = False) -> None:
        """Arm a delayed callback. The wrapper re-checks the cancelled
        flag under the timer lock at fire time, so a timer whose
        callback races cancel() becomes a no-op instead of mutating a
        replica the teardown already owns. ``restore=True`` marks fn
        as restorative: settle() runs it early (once) if the run ends
        before the delay elapses."""
        with self._timers_lock:
            if self._cancelled:
                return
            if restore:
                self._restore_seq += 1
                token = self._restore_seq
                self._restores[token] = fn
            else:
                token = None

        def guarded() -> None:
            with self._timers_lock:
                if self._cancelled:
                    return
                if token is not None:
                    # claim the restore: settle() must not run it twice
                    if self._restores.pop(token, None) is None:
                        return
                    self._restores_inflight += 1
            if token is None:
                fn()
                return
            try:
                fn()
            finally:
                with self._restores_done:
                    self._restores_inflight -= 1
                    self._restores_done.notify_all()

        t = threading.Timer(delay_s, guarded)
        t.daemon = True
        with self._timers_lock:
            if self._cancelled:
                return
            self._timers.append(t)
        t.start()

    # -------------------------------------------------------------- kinds
    def _restart_with_prime(self, victims: List[str]) -> None:
        """Restart each victim and replay the restarted agent's prime
        read: desired comes from the cluster, not from anything the
        dead process held. The cc.trace annotation rides the same node
        object (ISSUE 8), so a post-restart reconcile still joins the
        desired write's fleet-wide trace — exactly what the real
        agent's NodeWatcher.prime + latest_trace_context does after a
        DaemonSet restart."""
        for name in victims:
            replica = self.replicas[name]
            replica.restart()
            with self._timers_lock:
                # timeline thread (first upgrade cohort) and timer
                # threads both restart; the counter needs the lock
                self.restarted_total += 1
            try:
                node = self.ops_kube.get_node(name)
                meta = node["metadata"]
                desired = (meta.get("labels") or {}).get(
                    L.CC_MODE_LABEL
                )
                trace = (meta.get("annotations") or {}).get(
                    L.CC_TRACE_ANNOTATION
                )
            except ApiException:
                desired = None
                trace = None
            if desired is not None:
                self.pool.submit(name, desired, trace=trace)
            else:
                self.pool.requeue(name)  # drain anything it missed

    def _agent_crash(self, params: dict) -> dict:
        count = min(int(params["count"]), len(self.replicas))
        restart_after_s = float(params.get("restart_after_s", 1.0))
        victims = [
            name for name, r in sorted(self.replicas.items())
            if r.alive
        ][:count]
        for name in victims:
            self.replicas[name].crash()
        self.crashed_total += len(victims)
        # restorative: a run that converges while victims are still
        # down (they crashed already-converged) must end with the
        # restarts DONE, not cancelled at teardown — settle() runs
        # them early and waits them out
        self._timer(restart_after_s,
                    lambda: self._restart_with_prime(victims),
                    restore=True)
        return {"crashed": len(victims),
                "restart_after_s": restart_after_s}

    def _watch_drop(self, params: dict) -> dict:
        count = int(params["count"])
        with self.store._lock:
            self.store.fail_next_watches += count
        return {"count": count}

    def _watch_410(self, params: dict) -> dict:
        self.store.compact_watch_history()
        return {}

    def _list_429(self, params: dict) -> dict:
        count = int(params["count"])
        with self.store._lock:
            self.store.fail_next_lists += count
        return {"count": count}

    def _write_429(self, params: dict) -> dict:
        count = int(params["count"])
        with self.store._lock:
            self.store.fail_next_node_writes += count
        return {"count": count}

    def _throttle_squeeze(self, params: dict) -> dict:
        qps = float(params["qps"])
        duration_s = float(params["duration_s"])
        self.data_kube.set_qps(qps)
        self._timer(
            duration_s, lambda: self.data_kube.set_qps(self.base_qps),
            restore=True,
        )
        return {"qps": qps, "duration_s": duration_s}

    def _leader_flap(self, params: dict) -> dict:
        """Steal every election Lease for one term: the holder demotes
        at its next renew, the thief never renews, and a live replica
        re-acquires after staleness — adoption of any in-flight rollout
        record included."""
        from tpu_cc_manager.leader import _now_rfc3339

        stolen = []
        for name in self.lease_names:
            for _ in range(5):  # CAS retry against a racing renew
                try:
                    lease = self.ops_kube.get_lease(
                        self.lease_namespace, name
                    )
                except ApiException:
                    break  # no lease yet: nothing to steal
                spec = lease.setdefault("spec", {})
                spec["holderIdentity"] = "simlab-flap"
                spec["renewTime"] = _now_rfc3339()
                try:
                    self.ops_kube.replace_lease(
                        self.lease_namespace, name, lease
                    )
                    stolen.append(name)
                    break
                except (ConflictError, ApiException):
                    time.sleep(0.02)
        return {"leases_stolen": stolen}

    def _shard_kill(self, params: dict) -> dict:
        """Crash one controller shard host mid-run: its partition's
        lease goes stale (no release) and a surviving host must
        re-acquire it and resume the partition's controllers — the
        failover drill the shard_failover_convergence_s axis times."""
        if self.shard_manager is None:
            return {"skipped": "no shard manager"}
        host = int(params.get("host", 0))
        self.last_shard_kill_t = time.monotonic()
        entry = self.shard_manager.kill_host(host)
        restart_after_s = params.get("restart_after_s")
        if restart_after_s is not None:
            self._timer(
                float(restart_after_s),
                lambda: self.shard_manager.restart_host(host),
            )
            entry["restart_after_s"] = float(restart_after_s)
        return entry

    # --------------------------------------------- lifecycle (ISSUE 12)
    def _scoped(self, pool) -> List[str]:
        names = (self.nodes_in_pool(pool) if self.nodes_in_pool
                 else sorted(self.replicas))
        return [n for n in names if n in self.replicas]

    def _agent_upgrade(self, params: dict) -> dict:
        """Rolling agent upgrade: the scoped replicas restart cohort by
        cohort with a new code-version behavior, so for the rollout's
        duration TWO code versions reconcile one pool. Each cohort is
        a crash + version swap + prime-read restart — the DaemonSet
        rolling-update analog; the stagger is the maxUnavailable
        window."""
        version = params.get("version", "v2")
        cohorts = max(1, int(params.get("cohorts", 2)))
        stagger_s = float(params.get("stagger_s", 0.25))
        names = self._scoped(params.get("pool"))
        cohorts = min(cohorts, max(1, len(names)))
        groups = [names[i::cohorts] for i in range(cohorts)]

        def roll(group: List[str]) -> Callable[[], None]:
            def fire() -> None:
                for name in group:
                    self.replicas[name].upgrade(version)
                self._restart_with_prime(group)
            return fire

        for i, group in enumerate(groups):
            if not group:
                continue
            if i == 0:
                roll(group)()  # first cohort goes down NOW
            else:
                # restorative: the rolling upgrade must COMPLETE —
                # a cohort whose stagger lands after convergence is
                # rolled by settle() instead of dying with the run
                self._timer(i * stagger_s, roll(group), restore=True)
        self.upgraded_total += len(names)
        return {"nodes": len(names), "cohorts": len(groups),
                "version": version, "stagger_s": stagger_s}

    def _key_rotation(self, params: dict) -> dict:
        """Rotate the attestation signing key fleet-wide, mid-scan:
        every node's TPM signs with the new key from now on, and the
        verifier trust root gains the new primary with the old key in
        its rotation tail — so in-flight quotes stay verifiable while
        the next wave's evidence re-quotes under the new key. The
        invariants oracle then requires every node's settled evidence
        to verify under the NEW primary alone."""
        if self.attest_lab is None:
            return {"skipped": "attestation disabled"}
        return self.attest_lab.rotate()

    def _root_revoked(self, params: dict) -> dict:
        """Revoke the VERIFIER's attestation trust root. The nodes are
        fine and keep quoting; nobody can check them anymore — the
        audit's attestation_outage latch must fire (loud problem, not
        a metric fade) and the fleet must never read as verified
        again. Waits (bounded) for a fleet scan to VERIFY a quote
        first: the latch only arms on a once-verified fleet, so
        revoking earlier would drill nothing.

        ``forge=true`` adds the node-root drill on top: one
        already-converged node's agent is killed (root owns the node
        now) and a forged evidence document — device claims rewritten,
        re-quoted, re-digested, exactly what root CAN do — is planted
        in its place. The measured flip history inside the quote still
        contradicts the claim, which needs no verifier key to read."""
        if self.attest_lab is None:
            return {"skipped": "attestation disabled"}
        armed = False
        if self.attestation_armed is not None:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                if self.attestation_armed():
                    armed = True
                    break
                time.sleep(0.05)
        entry = self.attest_lab.revoke()
        entry["armed_before_revoke"] = armed
        if params.get("forge"):
            victim = self._pick_converged_node()
            if victim is None:
                entry["forged"] = None
                entry["forge_skipped"] = "no converged node to forge"
            else:
                # root took the node: the honest agent is dead and
                # stays dead, so the forged document cannot be healed
                # away by a later honest publish
                replica = self.replicas[victim]
                replica.crash()
                self.crashed_total += 1
                # deliver the dead agent's pending publications FIRST:
                # the forgery replaces the node's settled document —
                # a straggler honest flush overwriting the plant would
                # make the drill test nothing
                try:
                    replica.batcher.flush()
                except Exception:
                    log.warning("victim flush failed", exc_info=True)
                claim = self._contradicting_claim(replica)
                from tpu_cc_manager.evidence import forge_evidence_claim
                import json as _json

                doc = forge_evidence_claim(
                    victim, replica.backend, claim,
                    attestor=replica.attestor,
                )
                # out-of-band store write: root writes the annotation
                # with its own credentials, not through the system
                # under test's flow-controlled clients
                self.store.set_node_labels_direct(victim, {}, annotations={
                    L.EVIDENCE_ANNOTATION: _json.dumps(
                        doc, sort_keys=True, separators=(",", ":")
                    ),
                })
                self.attest_lab.note_forged(victim, claim, doc)
                entry["forged"] = victim
                entry["forged_claim"] = claim
        return entry

    def _pick_converged_node(self) -> Optional[str]:
        """First node (deterministic order) whose state label already
        reads the converge mode — the forgery victim must not owe the
        fleet any further convergence. Bounded wait: the drill runs
        after the final wave, so someone converges soon."""
        if self.converge_mode is None:
            return None
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            for name in sorted(self.replicas):
                try:
                    state = self.store.peek_node_label(
                        name, L.CC_MODE_STATE_LABEL
                    )
                except ApiException:
                    continue
                if state == self.converge_mode:
                    return name
            time.sleep(0.05)
        return None

    def _contradicting_claim(self, replica) -> str:
        """A claim mode that contradicts the victim's measured flip
        history (what a forger claims is by definition not what the
        measured engine path last did)."""
        from tpu_cc_manager.attest import measured_mode

        measured = None
        if replica.attestor is not None:
            try:
                _, events = replica.attestor._read_state()
                measured = measured_mode(events)
            except Exception:  # ccaudit: allow-swallow(unreadable TPM state just means no measured mode; the claim falls back to a fixed contradiction)
                measured = None
        return "off" if measured != "off" else "on"

    def _policy_conflict(self, params: dict) -> dict:
        """Two policies claiming overlapping pools: the OWNER (first in
        name order) selects the whole fleet; the RIVAL selects one
        pool inside it with a different target mode. The controller's
        name-ordered claim rule must park the rival in phase
        Conflicted — patching nothing — while the owner converges the
        fleet; the oracle pins both."""
        if self.create_policy is None:
            return {"skipped": "no policy surface"}
        pool = params.get("pool", 0)
        owner = self.create_policy(
            name="aa-conflict-owner", mode=params["mode"], pool=None,
        )
        rival = self.create_policy(
            name="zz-conflict-rival", mode=params["rival_mode"],
            pool=pool,
        )
        return {"owner": owner["policy"], "owner_mode": params["mode"],
                "rival": rival["policy"],
                "rival_mode": params["rival_mode"], "pool": pool}

    def _flip_latency(self, params: dict) -> dict:
        """Inject device-reset latency into the scoped replicas' fake
        chips (ISSUE 15): flips still SUCCEED, just slowly — the
        scripted anomaly the watchdog must catch live, with the guilty
        phase (``reset``) on the worker threads' stacks for the
        profiler and the slow reconciles' trace ids in the histogram
        exemplars. ``duration_s`` restores the original latency via a
        restorative timer (settle() runs it early on a fast run)."""
        delay_s = float(params["delay_s"])
        names = self._scoped(params.get("pool"))
        count = min(int(params.get("count", len(names))), len(names))
        victims = names[:count]
        # capture each chip's PRIOR latency before clobbering it, so
        # the restore puts back what was there — not a hardcoded 0
        # that would cancel an overlapping flip_latency fault (or a
        # scenario-configured baseline) early
        prior: List[tuple] = []
        for name in victims:
            for chip in self.replicas[name].backend.chips:
                prior.append((chip, chip._reset_latency_s))
                chip.set_reset_latency(delay_s)
        duration_s = params.get("duration_s")
        entry = {"nodes": len(victims), "delay_s": delay_s}
        if duration_s is not None:
            def restore() -> None:
                for chip, was in prior:
                    chip.set_reset_latency(was)

            self._timer(float(duration_s), restore, restore=True)
            entry["duration_s"] = float(duration_s)
        return entry

    def _evacuation_drain(self, params: dict) -> dict:
        """Region-evacuation drain racing in-flight flips: cordon N
        nodes through the REAL write path (spec.unschedulable — the
        kubectl-drain analog) while the mode storm is in flight, then
        uncordon after duration_s. The cordon must neither stop
        reconciliation (agents are DaemonSets; they tolerate) nor
        survive the run (settle() runs the uncordon early if the run
        converges first)."""
        count = int(params["count"])
        duration_s = float(params.get("duration_s", 1.0))
        names = self._scoped(params.get("pool"))[:count]
        cordoned = []
        for name in names:
            try:
                self.ops_kube.patch_node(
                    name, {"spec": {"unschedulable": True}}
                )
                cordoned.append(name)
                with self._timers_lock:
                    self.fault_write_units += 1
            except ApiException:
                log.warning("evacuation cordon failed for %s", name,
                            exc_info=True)
        self.evacuated_nodes.extend(cordoned)

        def uncordon() -> None:
            for name in cordoned:
                try:
                    self.ops_kube.patch_node(
                        name, {"spec": {"unschedulable": False}}
                    )
                    with self._timers_lock:
                        self.fault_write_units += 1
                except ApiException:
                    log.warning("evacuation uncordon failed for %s",
                                name, exc_info=True)

        self._timer(duration_s, uncordon, restore=True)
        return {"cordoned": len(cordoned), "duration_s": duration_s}

    # ----------------------------------------------------------- teardown
    def settle(self) -> None:
        """Run outstanding RESTORATIVE callbacks early (uncordon,
        throttle restore) and wait out ones already executing on a
        timer thread: a run that converges before (or during) their
        delay still ends in the restored state the invariants oracle
        judges. Each restore runs exactly once — here or in its
        timer, never both."""
        while True:
            with self._timers_lock:
                if self._cancelled:
                    return
                if not self._restores:
                    break
                token = next(iter(self._restores))
                fn = self._restores.pop(token)
                self._restores_inflight += 1
            try:
                fn()
            finally:
                with self._restores_done:
                    self._restores_inflight -= 1
                    self._restores_done.notify_all()
        deadline = time.monotonic() + 15.0
        with self._restores_done:
            while (self._restores_inflight > 0
                   and not self._cancelled
                   and time.monotonic() < deadline):
                self._restores_done.wait(timeout=0.1)
            if self._restores_inflight > 0:
                log.warning(
                    "settle: %d restorative callback(s) still in "
                    "flight after 15s", self._restores_inflight,
                )

    def cancel(self) -> None:
        """Cancel undelivered timers (teardown). A timer callback that
        already fired past Timer.cancel() re-checks the cancelled flag
        under the lock and becomes a no-op — it never mutates a
        torn-down replica (pinned by tests/test_simlab.py)."""
        with self._timers_lock:
            self._cancelled = True
            timers = list(self._timers)
            self._timers.clear()
            self._restores.clear()
        for t in timers:
            t.cancel()
