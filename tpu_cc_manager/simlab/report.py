"""Scenario artifact: the JSON document one simlab run leaves behind.

The artifact is the scenario's evidence — the convergence number the
bench trend gate compares (``pool<N>_convergence_s``), the watch-pump
lag distribution, the throttle-wait histogram delta, and the per-phase
p50 attribution — stamped with enough context (scenario name, limits,
fault log) that a regression reader can re-run the exact load."""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

ARTIFACT_VERSION = 1


def percentile(samples: List[float], q: float) -> Optional[float]:
    if not samples:
        return None
    s = sorted(samples)
    return round(s[min(len(s) - 1, max(0, int(q * len(s))))], 5)


def phase_percentiles(durations: Dict[str, List[float]],
                      q: float) -> Dict[str, float]:
    out = {}
    for name, durs in sorted(durations.items()):
        p = percentile(durs, q)
        if p is not None:
            out[name] = p
    return out


def convergence_key(nodes: int) -> str:
    """The trend-gated metric name: ``pool256_convergence_s`` for a
    256-node scenario (scripts/bench_trend.py compares it)."""
    return f"pool{nodes}_convergence_s"


def build_artifact(
    scenario,
    *,
    ok: bool,
    initial_convergence_s: Optional[float],
    convergence_s: Optional[float],
    pending: List[str],
    pump_stats: dict,
    throttle: dict,
    phase_durations: Dict[str, List[float]],
    replica_stats: dict,
    faults: List[dict],
    controllers: dict,
    trace_stitch: Optional[dict] = None,
    slo: Optional[dict] = None,
    incidents: Optional[dict] = None,
    shards: Optional[dict] = None,
    lifecycle: Optional[dict] = None,
    kube_io: Optional[dict] = None,
    federation: Optional[dict] = None,
    notes: Optional[str] = None,
) -> dict:
    metrics = {
        convergence_key(scenario.nodes): (
            round(convergence_s, 4) if convergence_s is not None else None
        ),
        "initial_convergence_s": (
            round(initial_convergence_s, 4)
            if initial_convergence_s is not None else None
        ),
        "watch_pump": pump_stats,
        "throttle": throttle,
        "phase_p50_s": phase_percentiles(phase_durations, 0.50),
        "phase_p95_s": phase_percentiles(phase_durations, 0.95),
        "reconciles": replica_stats,
    }
    if trace_stitch is not None:
        # the fleet-timeline stitch (runner._stitch_traces, ISSUE 8):
        # cross-process causal traces joined by trace id, and the
        # trend-gated end-to-end convergence latency derived from them
        metrics["trace_stitch"] = trace_stitch
        metrics["e2e_convergence_p99_s"] = trace_stitch.get(
            "e2e_convergence_p99_s")
    if shards is not None:
        # the sharded control plane's block (shard.py, ISSUE 11): ring
        # partition + live coverage, the lease handoff log, merged
        # fleet-view validity, and — when a shard_kill fault fired —
        # the kill -> fleet-converged latency the
        # shard_failover_convergence_s bench axis gates
        metrics["shards"] = shards
        metrics["shard_failover_convergence_s"] = shards.get(
            "failover_convergence_s")
    if lifecycle is not None:
        # the lifecycle-fault surface (ISSUE 12): code versions running
        # at quiescence, upgrade/evacuation accounting, and the
        # attestation lab's rotation/revocation/forgery record — what
        # the propgen invariants oracle judged, preserved for a
        # regression reader
        metrics["lifecycle"] = lifecycle
    if kube_io is not None:
        # which I/O core served the data plane (ISSUE 13): "aio" in
        # shared-loop mode, with the async client's dials/requests/
        # replays accounting — dials << requests is the multiplexing
        # the mode exists to prove
        metrics["kube_io"] = kube_io
    if federation is not None:
        # the multi-region block (federation.py, ISSUE 16): per-region
        # node-read ledgers (the zero-cross-region-reads evidence),
        # posture + evacuation record, per-region attestation audit,
        # and — when a region_evacuate fault fired AND the fleet
        # stabilized — the region_evac_convergence_s axis the bench
        # trend gate compares (absent on a failed drill, never a lie)
        metrics["federation"] = federation
        if "region_evac_convergence_s" in federation:
            metrics["region_evac_convergence_s"] = federation[
                "region_evac_convergence_s"]
    if slo is not None:
        # the fleet observatory's verdict (fleetobs.py, ISSUE 9):
        # per-objective burn rates + budget remaining, the alert log,
        # and the scrape/aggregation-validity accounting — or an
        # honest {"skipped": reason} when the engine couldn't run
        metrics["slo"] = slo
    if incidents is not None:
        # the anomaly watchdog's autopsy record (watchdog.py, ISSUE
        # 15): incident packets with window stats, exemplar trace ids
        # (resolved against the fleet-wide trace stitch), the live
        # profile, and capture latency — the in-run proof the
        # metrics → anomaly → exemplar → profile chain closed
        metrics["incidents"] = incidents
    artifact = {
        "artifact_version": ARTIFACT_VERSION,
        "scenario": scenario.name,
        "nodes": scenario.nodes,
        "ok": ok,
        "metrics": metrics,
        "faults": faults,
        "controllers": controllers,
        "limits": {
            "workers": scenario.workers,
            "qps": scenario.qps,
            "pools": scenario.pools,
            "chips_per_node": scenario.chips_per_node,
            "evidence": scenario.evidence,
        },
    }
    if pending:
        # name the stragglers: a failed run's artifact must be a lead,
        # not just a false
        artifact["pending_nodes"] = sorted(pending)[:16]
        artifact["pending_count"] = len(pending)
    if notes:
        artifact["notes"] = notes
    return artifact


def write_artifact(path: str, artifact: dict) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
