"""simlab — the fleet-scale scenario lab.

Runs hundreds of LIVE reconciling agent replicas in one process against
the wire-level :class:`~tpu_cc_manager.k8s.apiserver.FakeApiServer`,
executes a declarative scenario (mode storms, policy-driven rollouts,
scripted faults: agent crashes, watch drops, 410/429 storms, throttle
squeezes, leader flaps) and emits a JSON artifact carrying convergence
wall clock, watch-pump lag distribution, throttle-wait histogram
deltas, and per-phase p50 attribution from the trace spans.

Why it exists: the bench validates the agent at 32 live nodes and the
256-node scale tests drive controller scans over STATIC reports
(tests/test_scale.py) — the load the QPS token bucket exists for was
never manufactured with live churn (VERDICT r5 weak #4). simlab is the
subsystem whose whole job is manufacturing that evidence.

Design constraints (1-core sandbox):

- replicas are NOT thread-per-node agents: one shared watch pump fans
  label events out to per-replica last-value mailboxes, and a small
  worker pool executes reconciles — 256 replicas cost ~1 pump thread +
  N worker threads, not 768 blocked agent threads;
- every API interaction still crosses the real HTTP wire (shared
  flow-controlled clients), so throttle behavior and watch-stream
  robustness are measured, not simulated.

Modules: :mod:`scenario` (schema + validation), :mod:`replica`
(replica shell + worker pool), :mod:`pump` (shared watch pump),
:mod:`faults` (scripted fault injector), :mod:`runner` (orchestration),
:mod:`report` (artifact writer). CLI: ``python -m tpu_cc_manager
simlab run scenarios/smoke-64.json``; see docs/simlab.md.
"""

from __future__ import annotations


def main_from_args(args) -> int:
    """CLI dispatch for the ``simlab`` subcommand (called by
    tpu_cc_manager.__main__)."""
    import json
    import sys

    from tpu_cc_manager.simlab.scenario import (
        ScenarioError, load_scenario,
    )

    if args.simlab_command == "validate":
        bad = 0
        for path in args.scenarios:
            try:
                sc = load_scenario(path)
            except ScenarioError as e:
                print(f"{path}: INVALID: {e}", file=sys.stderr)
                bad += 1
                continue
            print(f"{path}: ok ({sc.nodes} nodes, "
                  f"{len(sc.actions)} actions)")
        return 1 if bad else 0

    if args.simlab_command == "run":
        from tpu_cc_manager.simlab.report import write_artifact
        from tpu_cc_manager.simlab.runner import SimLab

        try:
            sc = load_scenario(args.scenario)
        except ScenarioError as e:
            print(f"{args.scenario}: INVALID: {e}", file=sys.stderr)
            return 1
        if args.nodes:
            sc = sc.scaled_to(args.nodes)
        if args.workers:
            sc = sc.with_workers(args.workers)
        if sc.regions:
            # schema-2 multi-region scenario: N API servers, one fleet
            from tpu_cc_manager.simlab.federation import FederationLab

            artifact = FederationLab(sc).run()
        else:
            artifact = SimLab(sc).run()
        if args.out:
            write_artifact(args.out, artifact)
        print(json.dumps(artifact, sort_keys=True))
        return 0 if artifact["ok"] else 1

    if args.simlab_command == "propgen":
        from tpu_cc_manager.simlab.propgen import main_from_args as _pg

        return _pg(args)

    print("usage: simlab {run,validate,propgen} ... (see --help)",
          file=sys.stderr)
    return 2
