"""Scenario schema + strict validation for the simlab fleet lab.

A scenario is one JSON document describing a fleet (node count, pools,
chips per node), the lab's execution limits (worker slots, client-side
QPS), an action timeline (mode storms, policy creation, scripted
faults), and the convergence expectation the run is judged against.

Validation is STRICT — unknown keys anywhere in the document are
rejected. That strictness is what lets tests/test_simlab.py freshness-
gate the committed ``scenarios/*.json`` examples the same way
test_manifests.py gates the kustomize tree: a schema change that
orphans an example fails CI instead of rotting silently. The committed
files must also match :func:`canonical_scenario_text` byte for byte
(``python -m tpu_cc_manager simlab validate`` checks parse/semantics;
the test checks formatting freshness).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List

from tpu_cc_manager.modes import InvalidModeError, Mode, parse_mode

#: bumped on breaking schema changes; scenarios carry it explicitly so
#: a future reader can refuse documents it does not understand
SCENARIO_VERSION = 1

#: additive schema revision (ISSUE 16): ``"schema": 2`` unlocks the
#: federation surface (``regions``, region faults, per-region set_mode
#: windows). Deliberately a SEPARATE key from ``version`` — version
#: stays the breaking-change gate pinned at 1 (a v2 *version* must
#: still be refused), while schema is the opt-in for additions a v1
#: reader would reject as unknown keys.
SCENARIO_SCHEMA_MAX = 2

#: fault kinds that only exist under schema 2 + ``regions``
REGION_FAULTS = frozenset({
    "region_partition", "region_blackout", "region_latency_skew",
    "region_evacuate",
})

#: fault kind -> {param: (required, type(s))}
FAULT_PARAMS: Dict[str, Dict[str, tuple]] = {
    # crash N replicas; they stop reconciling and restart (re-reading
    # their node's desired label) after restart_after_s
    "agent_crash": {"count": (True, int),
                    "restart_after_s": (False, (int, float))},
    # the next N watch (re)connects fail server-side (FakeKube
    # fail_next_watches): the pump must absorb the storm and reconnect
    "watch_drop": {"count": (True, int)},
    # compact the watch history: the pump's next resume 410s and it
    # must full-relist to resynchronize
    "watch_410": {},
    # the next N node LISTs answer 429 (apiserver overload storm):
    # relists and controller scans must retry through it
    "list_429": {"count": (True, int)},
    # the next N node WRITES (patch/replace) answer 429: the write-path
    # storm the coalescing publish core (k8s.batch) must absorb —
    # state writes re-enter via replica repair, deferred evidence
    # retries with backoff, and the newest generation still lands
    "write_429": {"count": (True, int)},
    # squeeze the shared data-plane client's token bucket to qps for
    # duration_s, then restore the scenario's configured rate
    "throttle_squeeze": {"qps": (True, (int, float)),
                         "duration_s": (True, (int, float))},
    # steal the policy controllers' election Lease for one lease term:
    # the leader demotes mid-rollout and a replica must take over and
    # adopt the unfinished record
    "leader_flap": {},
    # crash one controller shard host (no lease release — survivors
    # must wait out shard-lease staleness, then re-acquire its
    # partition); optional restart brings it back as a standby. The
    # repartition storm is several of these in sequence. Requires
    # controllers.shards > 0.
    "shard_kill": {"host": (False, int),
                   "restart_after_s": (False, (int, float))},
    # ---- lifecycle fault families (ISSUE 12) -------------------------
    # rolling agent upgrade: the pool's replicas restart cohort by
    # cohort with a new code-version behavior, so two versions
    # reconcile one pool mid-rollout; upgraded replicas advertise
    # their version via the cc.agent-version annotation riding a
    # carrier write (zero extra round trips)
    "agent_upgrade": {"pool": (False, int),
                      "cohorts": (False, int),
                      "stagger_s": (False, (int, float)),
                      "version": (False, str)},
    # rotate the attestation signing key fleet-wide mid-scan: every
    # node's TPM signs with the new key, the verifier keeps the old
    # key in its rotation tail, and the next wave's evidence must
    # re-verify cleanly (requires `attestation`)
    "key_rotation": {},
    # revoke the VERIFIER's attestation trust root: nodes keep
    # quoting, nobody can check them — the fleet's attestation_outage
    # latch must fire and the fleet must never read as verified again.
    # `forge` additionally plants a node-root forged evidence document
    # (statefile-rewrite analog) on one already-converged node, which
    # must land in attestation_mismatch, never be accepted, and never
    # flip a chip (requires `attestation` + a fleet audit plane)
    "root_revoked": {"forge": (False, bool),
                     # schema 2: revoke ONE region's trust domain
                     # instead of the process-global root — the
                     # region_attestation_latch invariant's input
                     "region": (False, str)},
    # two policies claiming overlapping pools: an owner policy (first
    # in name order) selecting the whole fleet and a rival selecting
    # one pool. The name-ordered conflict rule must park the rival in
    # phase Conflicted while the owner converges the fleet (requires
    # controllers.policy)
    "policy_conflict": {"mode": (True, str),
                        "rival_mode": (True, str),
                        "pool": (False, int)},
    # region-evacuation drain racing in-flight flips: cordon N nodes
    # (spec.unschedulable, a real API write) while a mode storm is in
    # flight, uncordon after duration_s — the cordon must neither stop
    # reconciliation nor survive the run
    "evacuation_drain": {"count": (True, int),
                         "pool": (False, int),
                         "duration_s": (False, (int, float))},
    # inject delay_s of device-reset latency into the scoped replicas'
    # fake chips (the scripted slow-flip, ISSUE 15): reconciles still
    # SUCCEED, just slowly — the fault the anomaly watchdog must
    # notice live, name the guilty phase for, and autopsy. Optional
    # duration_s restores the original latency (restorative timer)
    "flip_latency": {"delay_s": (True, (int, float)),
                     "count": (False, int),
                     "pool": (False, int),
                     "duration_s": (False, (int, float))},
    # ---- federation fault family (ISSUE 16, schema 2 + regions) ------
    # region partition: the region's API server refuses every verb
    # (503) for duration_s — posture writes must defer and land when
    # it heals; the other regions keep converging
    "region_partition": {"region": (True, str),
                         "duration_s": (False, (int, float))},
    # regional API blackout: same 503 front door, scripted as the
    # total-control-plane-outage variant (in-flight watches sever too)
    "region_blackout": {"region": (True, str),
                        "duration_s": (False, (int, float))},
    # inter-region latency skew: every API verb in the region pays
    # delay_s before answering (slept outside the store lock)
    "region_latency_skew": {"region": (True, str),
                            "delay_s": (True, (int, float)),
                            "duration_s": (False, (int, float))},
    # first-class region evacuation: park the region's posture writes,
    # cordon its nodes, collapse every other region's window to NOW —
    # the evac-races-upgrade interleaving is this at mid-rollout
    "region_evacuate": {"region": (True, str)},
}

#: action kind -> {param: (required, type(s))}; "fault" params are
#: validated separately against FAULT_PARAMS
ACTION_PARAMS: Dict[str, Dict[str, tuple]] = {
    # patch the desired-mode label on every node (or one pool).
    # ``windows`` (schema 2 + regions): {region: offset seconds} —
    # per-region rollout windows for ONE posture, federation.py's
    # FleetPosture.windows verbatim
    "set_mode": {"mode": (True, str), "pool": (False, int),
                 "windows": (False, dict)},
    # create a TPUCCPolicy covering every node (or one pool); requires
    # controllers.policy
    "create_policy": {"mode": (True, str), "pool": (False, int),
                      "max_unavailable": (False, int),
                      "group_timeout_s": (False, (int, float))},
    "fault": {},  # validated per fault kind
}


class ScenarioError(ValueError):
    """A scenario document failed validation."""


@dataclasses.dataclass(frozen=True)
class Action:
    at: float
    kind: str
    params: dict


@dataclasses.dataclass(frozen=True)
class Controllers:
    fleet: bool = False
    policy: bool = False
    leader_elect: bool = False
    #: 0 = the single fleet/policy controller pair; N > 0 = N
    #: consistent-hash controller shards (tpu_cc_manager.shard), each
    #: holding a per-shard lease and running partition-scoped
    #: controllers over ONE shared node informer
    shards: int = 0


@dataclasses.dataclass(frozen=True)
class Converge:
    mode: str
    timeout_s: float = 120.0


@dataclasses.dataclass(frozen=True)
class RegionDef:
    """One federation region (schema 2): its own FakeApiServer, its
    slice of the fleet's nodes and pools, its own attestation trust
    domain. The top-level ``nodes``/``pools`` stay the fleet totals
    (and must equal the region sums) so every nodes-derived knob —
    CLI overrides, bench axes, fault count clamps — keeps meaning
    what it always meant."""

    name: str
    nodes: int
    pools: int = 1


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    nodes: int
    converge: Converge
    actions: List[Action]
    pools: int = 1
    chips_per_node: int = 1
    initial_mode: str = Mode.OFF.value
    workers: int = 8
    qps: float = 0.0
    evidence: bool = False
    #: per-replica software TPMs + a lab-provisioned verifier trust
    #: root (TPU_CC_TPM_KEY for the run only): evidence carries real
    #: quotes over real measured flip histories, so the key_rotation /
    #: root_revoked lifecycle faults act on live attestation state.
    #: Requires `evidence` (quotes ride evidence documents).
    attestation: bool = False
    watch_timeout_s: float = 10.0
    controllers: Controllers = Controllers()
    #: schema revision the document declared (1 when absent)
    schema: int = 1
    #: federation regions (schema 2); empty = the classic one-server lab
    regions: tuple = ()

    def scaled_to(self, nodes: int) -> "Scenario":
        """CLI --nodes override (fault counts are clamped at runtime)."""
        if nodes < 1:
            raise ScenarioError(f"nodes override must be >= 1, got {nodes}")
        if self.regions:
            raise ScenarioError(
                "--nodes cannot override a regions scenario (the "
                "per-region node split is part of the document)"
            )
        return dataclasses.replace(self, nodes=nodes)

    def with_workers(self, workers: int) -> "Scenario":
        if workers < 1:
            raise ScenarioError(
                f"workers override must be >= 1, got {workers}")
        return dataclasses.replace(self, workers=workers)


def _reject_unknown(doc: dict, allowed, where: str) -> None:
    unknown = sorted(set(doc) - set(allowed))
    if unknown:
        raise ScenarioError(
            f"{where}: unknown key(s) {unknown}; allowed: "
            f"{sorted(allowed)}"
        )


def _mode(value, where: str) -> str:
    if not isinstance(value, str):
        raise ScenarioError(f"{where}: mode must be a string")
    try:
        parse_mode(value)
    except InvalidModeError as e:
        raise ScenarioError(f"{where}: {e}") from None
    return value


def _typed(doc: dict, spec: Dict[str, tuple], where: str) -> None:
    for key, (required, types) in spec.items():
        if key not in doc:
            if required:
                raise ScenarioError(f"{where}: missing required {key!r}")
            continue
        if isinstance(doc[key], bool) and types is not bool:
            # bool is an int subclass; an accidental true where a count
            # belongs must not validate
            raise ScenarioError(f"{where}: {key!r} must be {types}")
        if not isinstance(doc[key], types):
            raise ScenarioError(f"{where}: {key!r} must be {types}, "
                                f"got {type(doc[key]).__name__}")


def _validate_action(raw: dict, idx: int, pools: int) -> Action:
    where = f"actions[{idx}]"
    if not isinstance(raw, dict):
        raise ScenarioError(f"{where}: must be an object")
    base_keys = {"at", "action"}
    if "action" not in raw:
        raise ScenarioError(f"{where}: missing required 'action'")
    kind = raw["action"]
    if kind not in ACTION_PARAMS:
        raise ScenarioError(
            f"{where}: unknown action {kind!r}; known: "
            f"{sorted(ACTION_PARAMS)}"
        )
    at = raw.get("at", 0.0)
    if isinstance(at, bool) or not isinstance(at, (int, float)) or at < 0:
        raise ScenarioError(f"{where}: 'at' must be a number >= 0")
    params = {k: v for k, v in raw.items() if k not in base_keys}
    if kind == "fault":
        fault = params.get("fault")
        if fault not in FAULT_PARAMS:
            raise ScenarioError(
                f"{where}: unknown fault {fault!r}; known: "
                f"{sorted(FAULT_PARAMS)}"
            )
        spec = FAULT_PARAMS[fault]
        _reject_unknown({k: v for k, v in params.items() if k != "fault"},
                        spec, f"{where} (fault {fault})")
        _typed(params, spec, f"{where} (fault {fault})")
        for key in ("count", "cohorts"):
            if key in spec and params.get(key, 1) < 1:
                raise ScenarioError(f"{where}: {key!r} must be >= 1")
        for key in ("mode", "rival_mode"):
            if key in params:
                _mode(params[key], f"{where} (fault {fault} {key})")
        if fault == "policy_conflict" and \
                params["mode"] == params["rival_mode"]:
            raise ScenarioError(
                f"{where}: policy_conflict mode and rival_mode must "
                "differ (identical claims are not a conflict)"
            )
        pool = params.get("pool")
        if pool is not None and not (0 <= pool < pools):
            raise ScenarioError(
                f"{where}: pool {pool} out of range [0, {pools})"
            )
    else:
        _reject_unknown(params, ACTION_PARAMS[kind], where)
        _typed(params, ACTION_PARAMS[kind], where)
        _mode(params["mode"], where)
        pool = params.get("pool")
        if pool is not None and not (0 <= pool < pools):
            raise ScenarioError(
                f"{where}: pool {pool} out of range [0, {pools})"
            )
    return Action(at=float(at), kind=kind, params=params)


def validate_scenario(doc: dict, source: str = None) -> Scenario:
    """Validate one parsed scenario document -> :class:`Scenario`.
    Raises :class:`ScenarioError` with a precise message on the first
    violation; ``source`` (the scenario file's path) prefixes every
    message so a CI sweep over scenarios/ names the offending FILE,
    not just the offending key."""
    try:
        return _validate_scenario(doc)
    except ScenarioError as e:
        if source:
            raise ScenarioError(f"{source}: {e}") from None
        raise


def _validate_scenario(doc: dict) -> Scenario:
    if not isinstance(doc, dict):
        raise ScenarioError("scenario must be a JSON object")
    allowed = {
        "version", "schema", "name", "nodes", "pools", "chips_per_node",
        "initial_mode", "workers", "qps", "evidence", "attestation",
        "watch_timeout_s", "controllers", "actions", "converge",
        "regions",
    }
    _reject_unknown(doc, allowed, "scenario")
    if doc.get("version") != SCENARIO_VERSION:
        raise ScenarioError(
            f"version must be {SCENARIO_VERSION}, got "
            f"{doc.get('version')!r} (refusing a schema this reader "
            "does not understand)"
        )
    # 'schema' is the ADDITIVE revision: absent = 1 (pre-federation
    # documents), 2 unlocks 'regions' and the region fault family.
    # Anything else is a document from the future — refuse it.
    schema = doc.get("schema", 1)
    if isinstance(schema, bool) or not isinstance(schema, int) or \
            not (1 <= schema <= SCENARIO_SCHEMA_MAX):
        raise ScenarioError(
            f"schema must be an int in [1, {SCENARIO_SCHEMA_MAX}], got "
            f"{schema!r}"
        )
    if "regions" in doc and schema < 2:
        raise ScenarioError(
            "regions requires \"schema\": 2 (a schema-1 reader would "
            "reject the key)"
        )
    _typed(doc, {
        "name": (True, str),
        "nodes": (True, int),
        "pools": (False, int),
        "chips_per_node": (False, int),
        "initial_mode": (False, str),
        "workers": (False, int),
        "qps": (False, (int, float)),
        "evidence": (False, bool),
        "attestation": (False, bool),
        "watch_timeout_s": (False, (int, float)),
    }, "scenario")
    nodes = doc["nodes"]
    if not (1 <= nodes <= 4096):
        raise ScenarioError(f"nodes must be in [1, 4096], got {nodes}")
    pools = doc.get("pools", 1)
    if not (1 <= pools <= nodes):
        raise ScenarioError(
            f"pools must be in [1, nodes={nodes}], got {pools}")
    chips = doc.get("chips_per_node", 1)
    if not (1 <= chips <= 8):
        raise ScenarioError(
            f"chips_per_node must be in [1, 8], got {chips}")
    regions: List[RegionDef] = []
    raw_regions = doc.get("regions")
    if raw_regions is not None:
        if not isinstance(raw_regions, list) or not raw_regions:
            raise ScenarioError("regions must be a non-empty array")
        for i, raw in enumerate(raw_regions):
            where = f"regions[{i}]"
            if not isinstance(raw, dict):
                raise ScenarioError(f"{where}: must be an object")
            _reject_unknown(raw, {"name", "nodes", "pools"}, where)
            _typed(raw, {"name": (True, str), "nodes": (True, int),
                         "pools": (False, int)}, where)
            if not raw["name"]:
                raise ScenarioError(f"{where}: name must be non-empty")
            if raw["nodes"] < 1:
                raise ScenarioError(f"{where}: nodes must be >= 1")
            rpools = raw.get("pools", 1)
            if not (1 <= rpools <= raw["nodes"]):
                raise ScenarioError(
                    f"{where}: pools must be in [1, nodes="
                    f"{raw['nodes']}], got {rpools}"
                )
            regions.append(RegionDef(name=raw["name"],
                                     nodes=raw["nodes"], pools=rpools))
        names = [r.name for r in regions]
        if len(set(names)) != len(names):
            raise ScenarioError(
                f"regions: duplicate region names {sorted(names)}"
            )
        if sum(r.nodes for r in regions) != nodes:
            raise ScenarioError(
                f"regions: per-region nodes sum to "
                f"{sum(r.nodes for r in regions)}, but nodes={nodes} "
                "(the top-level total must stay truthful)"
            )
        if sum(r.pools for r in regions) != pools:
            raise ScenarioError(
                f"regions: per-region pools sum to "
                f"{sum(r.pools for r in regions)}, but pools={pools}"
            )
    region_names = {r.name for r in regions}
    workers = doc.get("workers", 8)
    if not (1 <= workers <= 64):
        raise ScenarioError(f"workers must be in [1, 64], got {workers}")
    qps = doc.get("qps", 0.0)
    if qps < 0:
        raise ScenarioError(f"qps must be >= 0 (0 = unthrottled), got {qps}")
    watch_timeout_s = doc.get("watch_timeout_s", 10.0)
    if watch_timeout_s <= 0:
        raise ScenarioError("watch_timeout_s must be > 0")
    initial_mode = _mode(doc.get("initial_mode", Mode.OFF.value), "initial_mode")

    raw_ctl = doc.get("controllers", {})
    if not isinstance(raw_ctl, dict):
        raise ScenarioError("controllers must be an object")
    _reject_unknown(raw_ctl, {"fleet", "policy", "leader_elect",
                              "shards"}, "controllers")
    for key, value in raw_ctl.items():
        if key == "shards":
            if isinstance(value, bool) or not isinstance(value, int):
                raise ScenarioError("controllers.shards must be an int")
            if not (0 <= value <= 64):
                raise ScenarioError(
                    f"controllers.shards must be in [0, 64], got {value}"
                )
        elif not isinstance(value, bool):
            raise ScenarioError(f"controllers.{key} must be a bool")
    controllers = Controllers(**raw_ctl)
    if controllers.leader_elect and not controllers.policy:
        raise ScenarioError(
            "controllers.leader_elect requires controllers.policy "
            "(the Lease being flapped belongs to the policy pair)"
        )
    if controllers.shards:
        if not controllers.fleet:
            raise ScenarioError(
                "controllers.shards requires controllers.fleet (the "
                "sharded plane is the fleet/policy controllers; with "
                "neither there is nothing to shard)"
            )
        if controllers.leader_elect:
            raise ScenarioError(
                "controllers.shards and controllers.leader_elect are "
                "mutually exclusive (shard leases are their own "
                "election; the flapped policy-pair Lease does not "
                "exist in sharded mode)"
            )

    raw_conv = doc.get("converge")
    if not isinstance(raw_conv, dict):
        raise ScenarioError("converge is required and must be an object")
    _reject_unknown(raw_conv, {"mode", "timeout_s"}, "converge")
    _typed(raw_conv, {"mode": (True, str),
                      "timeout_s": (False, (int, float))}, "converge")
    timeout_s = raw_conv.get("timeout_s", 120.0)
    if timeout_s <= 0:
        raise ScenarioError("converge.timeout_s must be > 0")
    converge = Converge(mode=_mode(raw_conv["mode"], "converge"),
                        timeout_s=float(timeout_s))

    raw_actions = doc.get("actions")
    if not isinstance(raw_actions, list) or not raw_actions:
        raise ScenarioError("actions is required and must be a "
                            "non-empty array")
    actions = [
        _validate_action(a, i, pools) for i, a in enumerate(raw_actions)
    ]
    attestation = doc.get("attestation", False)
    if attestation and not doc.get("evidence", False):
        raise ScenarioError(
            "attestation requires evidence (quotes ride evidence "
            "documents; without evidence there is nothing to attest)"
        )
    for a in actions:
        if a.kind == "create_policy" and not controllers.policy:
            raise ScenarioError(
                "create_policy action requires controllers.policy"
            )
        if a.kind == "fault" and a.params["fault"] in (
                "key_rotation", "root_revoked"):
            if not attestation:
                raise ScenarioError(
                    f"{a.params['fault']} fault requires attestation "
                    "(there is no signing key to rotate or trust root "
                    "to revoke otherwise)"
                )
            if not (controllers.fleet or controllers.shards):
                raise ScenarioError(
                    f"{a.params['fault']} fault requires a fleet audit "
                    "plane (controllers.fleet or controllers.shards) — "
                    "the attestation verdicts and the outage latch "
                    "live in the fleet scan"
                )
        if (a.kind == "fault" and a.params["fault"] == "policy_conflict"
                and not controllers.policy):
            raise ScenarioError(
                "policy_conflict fault requires controllers.policy"
            )
        if (a.kind == "fault" and a.params["fault"] == "leader_flap"
                and not controllers.leader_elect):
            raise ScenarioError(
                "leader_flap fault requires controllers.leader_elect"
            )
        if a.kind == "fault" and a.params["fault"] == "shard_kill":
            if not controllers.shards:
                raise ScenarioError(
                    "shard_kill fault requires controllers.shards > 0"
                )
            host = a.params.get("host", 0)
            if not (0 <= host < controllers.shards):
                raise ScenarioError(
                    f"shard_kill host {host} out of range "
                    f"[0, {controllers.shards})"
                )
        # federation cross-checks: region faults / per-region windows /
        # region-scoped revocation only mean something with regions,
        # and every named region must exist
        if a.kind == "fault" and a.params["fault"] in REGION_FAULTS:
            if not regions:
                raise ScenarioError(
                    f"{a.params['fault']} fault requires 'regions' "
                    "(\"schema\": 2)"
                )
            if a.params["region"] not in region_names:
                raise ScenarioError(
                    f"{a.params['fault']}: unknown region "
                    f"{a.params['region']!r}; known: "
                    f"{sorted(region_names)}"
                )
        if (a.kind == "fault" and a.params["fault"] == "root_revoked"
                and "region" in a.params):
            if not regions:
                raise ScenarioError(
                    "root_revoked 'region' requires 'regions' "
                    "(\"schema\": 2)"
                )
            if a.params["region"] not in region_names:
                raise ScenarioError(
                    f"root_revoked: unknown region "
                    f"{a.params['region']!r}; known: "
                    f"{sorted(region_names)}"
                )
        if a.kind == "set_mode" and "windows" in a.params:
            if not regions:
                raise ScenarioError(
                    "set_mode 'windows' requires 'regions' "
                    "(\"schema\": 2)"
                )
            for rname, offset in a.params["windows"].items():
                if rname not in region_names:
                    raise ScenarioError(
                        f"set_mode windows: unknown region {rname!r}; "
                        f"known: {sorted(region_names)}"
                    )
                if isinstance(offset, bool) or not isinstance(
                        offset, (int, float)) or offset < 0:
                    raise ScenarioError(
                        f"set_mode windows[{rname!r}] must be a "
                        "number of seconds >= 0"
                    )
    return Scenario(
        name=doc["name"],
        nodes=nodes,
        pools=pools,
        chips_per_node=chips,
        initial_mode=initial_mode,
        workers=workers,
        qps=float(qps),
        evidence=doc.get("evidence", False),
        attestation=attestation,
        watch_timeout_s=float(watch_timeout_s),
        controllers=controllers,
        actions=sorted(actions, key=lambda a: a.at),
        converge=converge,
        schema=schema,
        regions=tuple(regions),
    )


def load_scenario(path: str) -> Scenario:
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise ScenarioError(f"cannot read {path}: {e}") from e
    except ValueError as e:
        raise ScenarioError(f"{path}: not valid JSON: {e}") from e
    return validate_scenario(doc, source=path)


def canonical_scenario_text(doc: dict) -> str:
    """The one true formatting for committed scenario files (2-space
    indent, sorted keys, trailing newline) — tests/test_simlab.py
    compares committed bytes against this, freshness-gate style."""
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"
