"""Fleet controller — the long-running operator-side audit service.

The per-node agents make each node converge; the rollout tool changes a
pool on purpose; this controller answers "what state is the fleet in
RIGHT NOW" continuously. It periodically lists the pool, runs the JAX
fleet planner (tpu_cc_manager.plan — one fused XLA computation over the
whole fleet), and serves:

- ``GET /metrics`` — pool-level Prometheus gauges: nodes per observed
  mode, divergence count, failed count, incoherent / half-flipped slice
  counts, scan duration;
- ``GET /report``  — the latest full fleet report as JSON (the same
  shape as ``python -m tpu_cc_manager.plan``);
- ``GET /healthz`` — liveness (scan loop alive and not persistently
  failing).

Deliberately read-only: remediation stays with the agents (per node)
and the rollout tool (operator-driven). The reference has no fleet-level
view at all — its operators grep node labels by hand (SURVEY.md §5.5).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import List, Optional

from tpu_cc_manager import labels as L
from tpu_cc_manager.evidence import audit_evidence
from tpu_cc_manager.k8s.client import KubeClient
from tpu_cc_manager.k8s.objects import match_selector
from tpu_cc_manager.obs import (
    OBSERVED_MODE_VALUES, Counter, Gauge, Histogram, RouteServer,
    kube_queue_rejected_counter, kube_throttle_wait_histogram,
    render_metric_set, wire_queue_reject_observer, wire_throttle_observer,
)
from tpu_cc_manager.plan import (
    FleetEncoding, TickSession, analyze_encoding, compile_stats,
)
from tpu_cc_manager.tsring import TimeSeriesRing

#: the shared node-watch pump and its wake filter moved to watch.py
#: (the watch layer owns delta delivery now that the planner's feature
#: block rides it); re-exported here for embedders and history
from tpu_cc_manager.watch import (  # noqa: F401
    FingerprintWakeFilter, node_report_fingerprint, run_node_watch,
)

log = logging.getLogger("tpu-cc-manager.fleet")


def fleet_problems(report: dict) -> List[str]:
    """The audit findings that mean an operator must look — the health
    classification ``fleet-controller --once`` (cron/CI) exits non-zero
    on. Lives here, next to the report shape, so a new report section
    is classified where it is produced. Divergence alone is NOT a
    problem (agents may simply still be converging); failures, evidence
    contradictions, failing doctor verdicts, and half-flipped slices
    are."""
    problems: List[str] = []
    if report.get("failed"):
        problems.append(f"failed nodes: {sorted(report['failed'])}")
    audit = report.get("evidence_audit") or {}
    # 'missing' IS a problem here: the audit only reports it for nodes
    # whose label claims a SUCCESSFUL mode with no evidence behind it —
    # the simplest forgery (no HMAC to defeat), or an agent that died
    # between labeling and committing. The ROLLOUT judge tolerates
    # missing evidence (pre-evidence agents must not brick a rollout);
    # an audit's job is suspicion, not tolerance.
    for issue in ("missing", "invalid", "label_device_mismatch"):
        if audit.get(issue):
            problems.append(f"evidence {issue}: {sorted(audit[issue])}")
    if audit.get("unsigned"):
        from tpu_cc_manager.evidence import UNSIGNED_RUNBOOK

        # deployment asymmetry, not forgery: say exactly what to fix
        problems.append(
            f"evidence unsigned under a keyed verifier: "
            f"{sorted(audit['unsigned'])} — these agents publish "
            "plain-hashed evidence while this controller holds the "
            f"pool key; {UNSIGNED_RUNBOOK}"
        )
    # 'unverifiable' (signed docs, unkeyed auditor) is deliberately NOT
    # a problem: it is the expected state mid-enablement (agents keyed
    # first). It stays visible via the evidence_issues metric.
    # 'stale_key' (verifies only under a rotation-tail key) likewise:
    # the sync healer re-signs on its own cadence; the bucket/metric
    # exists so the operator knows when the old key line can go.
    if audit.get("identity_mismatch"):
        # the forged-evidence drill: a document whose platform-identity
        # token speaks for another node (or fails verification) means
        # someone with the pool evidence key — but without control of
        # THIS node's metadata server — minted it
        problems.append(
            "evidence identity mismatch (token speaks for another "
            f"node or fails verification): "
            f"{sorted(audit['identity_mismatch'])}"
        )
    if audit.get("attestation_mismatch"):
        # the node-root drill: the document verifies under the pool key
        # and may even carry the node's own identity, but the TEE quote
        # contradicts it — nonce replay, bad quote signature, or a
        # device claim that disagrees with the measured flip history
        # (state changed outside the measured engine path)
        problems.append(
            "evidence attestation mismatch (TEE quote contradicts the "
            f"document): {sorted(audit['attestation_mismatch'])}"
        )
    if audit.get("attestation_outage"):
        # the verifier-side outage latch: quotes verified on an earlier
        # scan of this controller process, and now NONE do while nodes
        # still attach them — the nodes are fine; the VERIFIER lost its
        # trust root. Without this line the whole fleet degrades to an
        # attestation_unverifiable metric an operator has to know to
        # watch (VERDICT r5 weak #5).
        problems.append(
            "attestation went unverifiable fleet-wide after quotes had "
            "verified — likely the verifier lost its trust root "
            "(TPU_CC_TPM_KEY[_FILE] / TPU_CC_ATTESTATION_JWKS_FILE): "
            f"{sorted(audit['attestation_outage'])}"
        )
    if audit.get("attestation_missing"):
        # gated upstream like identity_missing: populated on mixed
        # pools or under TPU_CC_REQUIRE_ATTESTATION
        problems.append(
            "evidence lacks attestation on an attestation-bearing "
            f"pool: {sorted(audit['attestation_missing'])} — node root "
            "can re-sign evidence, but cannot mint a TEE quote whose "
            "measured history matches a forged claim"
        )
    if audit.get("identity_missing"):
        # populated on mixed pools, under TPU_CC_REQUIRE_IDENTITY, or
        # when an earlier scan of this controller process saw VERIFIED
        # identity (the fleet-wide-outage latch; audit_evidence's
        # identity_seen_before encodes all three)
        problems.append(
            "evidence lacks platform identity on an identity-bearing "
            f"pool: {sorted(audit['identity_missing'])} — a stolen "
            "pool key can sign evidence but cannot mint the node's "
            "instance identity token"
        )
    doctor = report.get("doctor") or {}
    if doctor.get("failing"):
        problems.append(
            "doctor failing: "
            f"{sorted(d['node'] for d in doctor['failing'])}"
        )
    if report.get("half_flipped_slices"):
        problems.append(
            f"half-flipped slices: {sorted(report['half_flipped_slices'])}"
        )
    if report.get("incoherent_slices"):
        # unlike plain divergence, incoherent DESIRED labels on one
        # slice can never self-converge — members hold in slice_wait
        # until an operator fixes the labels
        problems.append(
            f"incoherent slices: {sorted(report['incoherent_slices'])}"
        )
    return problems


class FleetMetrics:
    def __init__(self):
        self.nodes = Gauge("tpu_cc_fleet_nodes", "Nodes in the fleet")
        self.nodes_by_mode = Gauge(
            "tpu_cc_fleet_nodes_by_mode",
            "Nodes per observed mode", ("mode",),
        )
        self.needs_flip = Gauge(
            "tpu_cc_fleet_needs_flip",
            "Nodes whose observed mode diverges from desired",
        )
        self.failed = Gauge(
            "tpu_cc_fleet_failed_nodes", "Nodes reporting failed state"
        )
        self.incoherent_slices = Gauge(
            "tpu_cc_fleet_incoherent_slices",
            "Multi-host slices whose members disagree on desired/observed mode",
        )
        self.half_flipped_slices = Gauge(
            "tpu_cc_fleet_half_flipped_slices",
            "Multi-host slices stuck mid-flip (some members at target)",
        )
        self.evidence_issues = Gauge(
            "tpu_cc_fleet_evidence_issues",
            "Nodes failing the evidence-vs-label audit, by issue",
            ("issue",),
        )
        self.doctor_failing = Gauge(
            "tpu_cc_fleet_doctor_failing_nodes",
            "Nodes whose published doctor verdict has failing checks",
        )
        self.doctor_unreported = Gauge(
            "tpu_cc_fleet_doctor_unreported_nodes",
            "Nodes publishing no doctor verdict at all (the "
            "TPU_CC_WEBHOOK_REQUIRE_DOCTOR preflight: enforce only "
            "at zero)",
        )
        self.scans_total = Counter(
            "tpu_cc_fleet_scans_total", "Fleet scans, by outcome", ("outcome",)
        )
        self.scan_duration = Histogram(
            "tpu_cc_fleet_scan_duration_seconds",
            "Wall-clock duration of one fleet scan",
        )
        self.kube_throttle_wait = kube_throttle_wait_histogram()
        self.kube_queue_rejected = kube_queue_rejected_counter()
        # planner compile economics (ISSUE 8 satellite): mirrors of
        # plan.py's monotonic trace/compile-cache counters, refreshed
        # every scan — the PR-7 "restart = zero cache misses" claim
        # becomes scrapeable instead of only test-pinned
        self.planner_retraces = Counter(
            "tpu_cc_planner_retraces_total",
            "XLA (re)traces of planner kernels since process start, "
            "per kernel (steady state: one per shape bucket, ever)",
            ("kernel",),
        )
        self.planner_cache_hits = Counter(
            "tpu_cc_planner_compile_cache_hits_total",
            "Planner compiles served from the persistent compile "
            "cache (TPU_CC_COMPILE_CACHE_DIR)",
        )
        self.planner_cache_misses = Counter(
            "tpu_cc_planner_compile_cache_misses_total",
            "Planner compiles that missed the persistent compile "
            "cache (cold XLA paid; a warmed restart should add zero)",
        )
        self.planner_events_dropped = Counter(
            "tpu_cc_planner_events_dropped_total",
            "Malformed node-watch events dropped by the planner's "
            "feature block (FleetEncoding.apply_event) instead of "
            "thrown in a watch thread — nonzero means the API server "
            "is emitting node objects the encoder can't read",
        )

    def update(self, report: dict) -> None:
        self.nodes.set(report["nodes"])
        counts = report["mode_counts"]
        # the canonical vocabulary, so modes that vanished from the fleet
        # zero out instead of going stale
        for mode in OBSERVED_MODE_VALUES:
            self.nodes_by_mode.set(counts.get(mode, 0), mode)
        self.needs_flip.set(len(report["needs_flip"]))
        self.failed.set(len(report["failed"]))
        self.incoherent_slices.set(len(report["incoherent_slices"]))
        self.half_flipped_slices.set(len(report["half_flipped_slices"]))
        audit = report.get("evidence_audit", {})
        from tpu_cc_manager.evidence import EVIDENCE_ISSUE_KEYS

        # the canonical bucket vocabulary (shared with audit_evidence):
        # iterating a fixed tuple keeps zero-out semantics when a
        # bucket is absent from this scan's audit
        for issue in EVIDENCE_ISSUE_KEYS:
            self.evidence_issues.set(len(audit.get(issue, [])), issue)
        self.doctor_failing.set(
            len(report.get("doctor", {}).get("failing", []))
        )
        self.doctor_unreported.set(
            len(report.get("doctor", {}).get("unreported", []))
        )
        stats = compile_stats()
        for kernel, n in stats["retraces"].items():
            self.planner_retraces.set_total(n, kernel)
        self.planner_cache_hits.set_total(stats["cache_hits"])
        self.planner_cache_misses.set_total(stats["cache_misses"])

    def render(self) -> str:
        # reflection over every metric attribute (obs.registered_metrics):
        # adding a gauge above can no longer silently miss exposition
        return render_metric_set(self)


class FleetController:
    def __init__(
        self,
        kube: KubeClient,
        *,
        selector: str = L.TPU_ACCELERATOR_LABEL,
        interval_s: float = 30.0,
        port: int = 8090,
        max_consecutive_errors: int = 10,
        leader_elector=None,
        observer=None,
        informer=None,
        node_filter=None,
        attest_key=None,
    ):
        self.kube = kube
        self.selector = selector
        #: optional attestation trust root override (federation.py):
        #: None keeps the env posture (tpm_keys); a bytes/tuple value —
        #: or a zero-arg callable returning one, so a region's trust
        #: domain can rotate/revoke without rebuilding the controller —
        #: scopes this controller's quote judging to ONE trust domain.
        #: An empty tuple is a revoked domain: every quote reads
        #: 'unverifiable' and the outage latch fires for THIS
        #: controller only, never its siblings in other regions.
        self.attest_key = attest_key
        #: optional watch.NodeInformer (ISSUE 11): when set, this
        #: controller does NOT open its own node watch — it subscribes
        #: to the shared informer's delta/wake feed instead, and the
        #: caller typically hands an informer-backed ``kube`` so scans
        #: read fleet state from local memory (0 node read round trips
        #: in steady state, pinned by tests/test_shard.py)
        self.informer = informer
        self._informer_token = None
        #: the shared report-relevance wake filter for the informer
        #: feed (run_node_watch keeps its own instance internally);
        #: informer-delivery-thread-only after run() subscribes
        self._informer_wake_filter = FingerprintWakeFilter(self._wake_scan)
        #: optional partition predicate (shard.py): nodes failing it
        #: are invisible to this controller — the watch feed applies it
        #: exactly like the selector, so a shard's encoding never
        #: ingests a foreign partition's nodes
        self.node_filter = node_filter
        #: optional fleetobs.FleetObserver (ISSUE 9): when set, its
        #: burning-SLO lines join every report's problems digest and
        #: the fleet rollup exposition serves on /fleet/metrics. The
        #: observer's scrape loop belongs to whoever constructed it —
        #: this controller only *reads* it.
        self.observer = observer
        #: optional tpu_cc_manager.leader.LeaderElector: when set, run()
        #: scans only while holding the Lease (standby replicas stay
        #: hot but quiet — see policy.py's identical gating)
        self.leader_elector = leader_elector
        #: election reporting: namespace resolved ONCE at construction
        #: (embedders inject an elector with their own namespace; the
        #: env default matches _leader_elector in __main__), and the
        #: lease lookups are skipped entirely when election is off —
        #: no point paying two guaranteed-404 GETs per scan
        from tpu_cc_manager.config import _env_bool

        self._election_ns = os.environ.get(
            "OPERATOR_NAMESPACE", "tpu-system"
        )
        self._election_enabled = (
            leader_elector is not None
            or _env_bool("TPU_CC_LEADER_ELECT", False)
        )
        if interval_s <= 0:
            raise ValueError(
                f"scan interval must be > 0, got {interval_s!r} "
                "(a zero interval busy-loops against the API server)"
            )
        self.interval_s = interval_s
        self.max_consecutive_errors = max_consecutive_errors
        self.metrics = FleetMetrics()
        # the QPS token bucket's per-request wait lands on THIS
        # controller's /metrics — "is the limiter throttling us at
        # fleet scale?" must be a histogram, not a guess
        wire_throttle_observer(kube, self.metrics.kube_throttle_wait)
        # overload honesty: writes the aio admission gate refuses are
        # this controller's saturation signal (TPU_CC_KUBE_QUEUE)
        wire_queue_reject_observer(kube, self.metrics.kube_queue_rejected)
        self.last_report: Optional[dict] = None
        self.consecutive_errors = 0
        #: sticky across scans: once any scan sees an identity-bearing
        #: evidence document, a LATER uniform all-missing pool is a
        #: metadata outage to flag, not a never-on-GCE pool to ignore
        #: (audit_evidence's identity_seen_before). Process-local by
        #: design — deliberately decommissioning identity is
        #: acknowledged by restarting the controller
        self._identity_ever_seen = False
        #: attestation's twin latch: armed by the first VERIFIED quote;
        #: a later scan where every quote reads 'unverifiable' is then
        #: a verifier-trust-root outage (attestation_outage problem),
        #: not a metric-only fade. Same restart-to-acknowledge rule.
        self._attestation_ever_verified = False
        #: label-vs-evidence mismatch debounce (ISSUE 6): evidence now
        #: rides the coalescing publish core, so a scan racing a flip
        #: can see the new state label before the (deferred) evidence
        #: annotation lands — a transient, self-healing skew, not the
        #: lying-label attack. A node must stay mismatched across TWO
        #: consecutive scans to surface as a problem; first-scan hits
        #: are reported separately (label_device_mismatch_transient)
        #: so the skew stays visible without paging anyone.
        self._prior_label_mismatch: set = set()
        #: watch-triggered scans: a node watch wakes the scan loop the
        #: moment report-relevant state changes, so mode divergence /
        #: failed flips / doctor verdicts surface in seconds instead of
        #: at the next interval tick; the interval remains the liveness
        #: fallback. Bursts coalesce through the min scan gap.
        self._wake = threading.Event()
        #: the planner's per-node feature block (ISSUE 7): fed
        #: incrementally by the node watch's delta stream and
        #: fingerprint-diff-synced against each scan's list, so the
        #: per-scan encode cost tracks what CHANGED, not fleet size
        self._encoding = FleetEncoding()
        #: the planner's incremental tick state (ISSUE 19): device-
        #: resident sharded columns + the host mirror that lets a scan
        #: re-evaluate only the rows the watch feed dirtied. One
        #: session per controller; analyze_encoding(session=...) owns
        #: its rebuild/verify cadence.
        self._tick_session = TickSession()
        #: delta-feed trust (ISSUE 19): while a watch/informer feed is
        #: live, scans may SKIP the full list reconcile (`sync`) —
        #: apply_event already wrote every delta — and only resync on
        #: cadence or after a feed gap (reconnect / informer relist)
        #: flags that deltas may have been missed.
        #: guards the three feed flags below — written from the watch/
        #: informer threads, test-and-reset atomically by the scan
        self._feed_lock = threading.Lock()
        self._delta_feed_active = False
        self._resync_needed = True
        self._scans_since_sync = 0
        self.watch_timeout_s = 300
        self.watch_backoff_s = 5.0
        from tpu_cc_manager.config import _env_float

        self.min_scan_gap_s = _env_float(
            "TPU_CC_FLEET_MIN_SCAN_GAP_S", 5.0
        )
        self.sync_every = int(os.environ.get(
            "TPU_CC_FLEET_SYNC_EVERY", "8"
        ))
        self._stop = threading.Event()
        #: the controller's own metric history (tsring.py, ISSUE 9)
        self.tsring = TimeSeriesRing(self.metrics, name="fleet")
        #: the controller-side anomaly watchdog (watchdog.py, ISSUE
        #: 15): its own scan-path series — a scan-duration excursion
        #: or an API flow-control stall fires an incident with the
        #: offending window's stats on /debug/incidents
        from tpu_cc_manager.watchdog import WatchSeries, Watchdog

        self.watchdog = Watchdog(
            series=(
                WatchSeries(
                    "tpu_cc_fleet_scan_duration_seconds", "p99",
                    description="fleet scan duration",
                ),
                WatchSeries(
                    "tpu_cc_kube_throttle_wait_seconds", "p99",
                    min_scale=0.1,
                    description="API client flow-control waits",
                ),
            ),
            sources=[self.metrics], name="fleet",
        )
        self.tsring.add_listener(self.watchdog.consume)
        self._server = RouteServer(port, name="fleet-http")
        self._server.add_route("/healthz", self._healthz)
        self._server.add_route("/readyz", self._readyz)
        self._server.add_route("/metrics", self._metrics_route)
        self._server.add_route("/report", self._report_route)
        self._server.add_route("/debug/timeseries", self._timeseries_route)
        self._server.add_route("/debug/incidents", self._incidents_route)
        self._server.add_route("/fleet/metrics", self._fleet_metrics_route)

    @property
    def attestation_ever_verified(self) -> bool:
        """Has any scan of this controller process verified a TEE
        quote? This is the armed state of the ``attestation_outage``
        latch — simlab's revoked-root drill reads it so the revocation
        fires only AFTER the latch is armed (a fleet that never
        verified stays quiet by design, so revoking earlier would test
        nothing)."""
        return self._attestation_ever_verified

    # -------------------------------------------------------------- scans
    def scan_once(self) -> dict:
        t0 = time.monotonic()
        try:
            # Everything through metric publication is inside the counted
            # block: any failure (malformed node objects, JAX runtime
            # errors, metric-shape bugs) increments consecutive_errors and
            # degrades /healthz instead of crashing run() or — worse —
            # retrying forever with the error counter stuck at 0.
            nodes = self.kube.list_nodes(self.selector)
            if self.node_filter is not None:
                # shard partition scope: the scan sees exactly the
                # nodes the watch feed admits (filter symmetry keeps
                # encoding and list truth in agreement)
                nodes = [n for n in nodes if self.node_filter(n)]
            # list truth reconciles the watch-fed feature block
            # (unchanged nodes cost a fingerprint compare, not a
            # re-encode), then ONE jitted planner tick answers the
            # divergence/slice/doctor questions in a batch. With a
            # live delta feed the fingerprint sweep itself is skipped
            # between cadence resyncs — apply_event already wrote
            # every delta — but a feed gap forces the next scan to
            # reconcile (ISSUE 19).
            with self._feed_lock:
                do_sync = (not self._delta_feed_active
                           or self._resync_needed
                           or self._scans_since_sync >= self.sync_every)
                if do_sync:
                    # reset BEFORE the sync runs: a gap landing while
                    # we reconcile re-arms the flag for the next scan
                    self._resync_needed = False
                    self._scans_since_sync = 0
                else:
                    self._scans_since_sync += 1
            if do_sync:
                self._encoding.sync(nodes)
            report = analyze_encoding(
                self._encoding, session=self._tick_session
            )
            # label-vs-device truth: the JAX planner trusts label text;
            # the evidence audit cross-checks it against what each
            # node's agent independently attested (VERDICT r2 item 7)
            # resolve a callable trust root per scan (federation: the
            # region's domain may have been revoked since last tick)
            ak = (self.attest_key() if callable(self.attest_key)
                  else self.attest_key)
            audit = audit_evidence(
                nodes, identity_seen_before=self._identity_ever_seen,
                attestation_seen_before=self._attestation_ever_verified,
                attest_key=ak,
            )
            self._identity_ever_seen = (
                self._identity_ever_seen or audit.get("identity_seen", False)
            )
            self._attestation_ever_verified = (
                self._attestation_ever_verified
                or audit.get("attestation_seen", False)
            )
            cur_mismatch = set(audit.get("label_device_mismatch", []))
            audit["label_device_mismatch"] = sorted(
                cur_mismatch & self._prior_label_mismatch
            )
            audit["label_device_mismatch_transient"] = sorted(
                cur_mismatch - self._prior_label_mismatch
            )
            self._prior_label_mismatch = cur_mismatch
            report["evidence_audit"] = audit
            # report["doctor"] comes batched from the planner tick:
            # which nodes report failing trust-surface checks
            # (malformed verdicts count as failing), and which report
            # NOTHING — ``unreported`` is the preflight for
            # TPU_CC_WEBHOOK_REQUIRE_DOCTOR (enforce only at zero)
            report["policies"] = self._policy_summaries()
            report["leader_elections"] = self._election_summaries()
            # the actionable digest rides in the report itself, so the
            # live /report and `--once` stdout agree — an operator (or
            # alert rule) reads one field either way
            report["problems"] = fleet_problems(report)
            if self.observer is not None:
                # burning SLOs are fleet problems: the objective layer
                # degrades GRADUALLY (budget burn) before any binary
                # gate fails — surface it in the same digest
                report["problems"].extend(self.observer.problems())
                report["slo"] = self.observer.status()
            from tpu_cc_manager.trace import current_trace_ids

            # the active trace (if any) rides as the scan-latency
            # bucket's exemplar (ISSUE 15)
            self.metrics.scan_duration.observe(
                time.monotonic() - t0,
                trace_id=current_trace_ids()[0])
            self.metrics.update(report)
            # encoder-side drop total lives on the encoding (update()
            # never sees it — reports carry analysis, not ingest
            # health), mirrored here via the external-total pattern
            self.metrics.planner_events_dropped.set_total(
                float(self._encoding.events_dropped)
            )
            self.last_report = report
        except Exception:
            self.metrics.scans_total.inc("error")
            self.consecutive_errors += 1
            raise
        self.consecutive_errors = 0
        self.metrics.scans_total.inc("success")
        return report

    def _election_summaries(self) -> dict:
        """Election state of both controllers, so /report is the one
        pane for HA debugging too: who leads, for how long, how many
        failovers. Empty entries when the Lease doesn't exist (election
        disabled) or the client has no lease support."""
        out = {}
        if not self._election_enabled:
            return out
        for name in ("tpu-cc-policy-controller",
                     "tpu-cc-fleet-controller"):
            try:
                lease = self.kube.get_lease(self._election_ns, name)
            except Exception:
                log.debug("lease %s unreadable; omitting from status",
                          name, exc_info=True)
                continue
            spec = lease.get("spec") or {}
            out[name] = {
                "holder": spec.get("holderIdentity"),
                "acquireTime": spec.get("acquireTime"),
                "renewTime": spec.get("renewTime"),
                "transitions": spec.get("leaseTransitions", 0),
            }
        return out

    def _policy_summaries(self) -> List[dict]:
        """Status summaries of the cluster's TPUCCPolicies, so /report
        is the single operator pane. Empty when the CRD isn't installed
        (404) or the controller lacks CR read rights."""
        try:
            policies = self.kube.list_cluster_custom(
                L.POLICY_GROUP, L.POLICY_VERSION, L.POLICY_PLURAL
            )
        except Exception:  # ccaudit: allow-swallow(CRD absent or unreadable: /report simply omits the policies pane)
            return []
        out = []
        for p in policies:
            st = p.get("status") or {}
            out.append({
                "name": p["metadata"]["name"],
                "mode": (p.get("spec") or {}).get("mode"),
                "phase": st.get("phase"),
                "nodes": st.get("nodes"),
                "converged": st.get("converged"),
                "message": st.get("message"),
            })
        return sorted(out, key=lambda d: d["name"])

    @property
    def healthy(self) -> bool:
        return self.consecutive_errors < self.max_consecutive_errors

    @property
    def port(self) -> int:
        return self._server.port

    # -------------------------------------------------------------- routes
    def _healthz(self):
        return ((200, b"ok", "text/plain") if self.healthy
                else (503, b"unhealthy", "text/plain"))

    def _readyz(self):
        """Leader-aware readiness (see policy.py _readyz): standbys are
        healthy but not Ready, keeping Service traffic on the scanner."""
        if not self.healthy:
            return 503, b"unhealthy", "text/plain"
        if (self.leader_elector is not None
                and not self.leader_elector.is_leader):
            return 503, b"standby (not leader)", "text/plain"
        return 200, b"ok", "text/plain"

    def _metrics_route(self):
        # scan-histogram exemplars ride this render: OpenMetrics type
        # (obs.OPENMETRICS_CONTENT_TYPE rationale); the merged
        # /fleet/metrics below stays classic — the merge strips
        # exemplars by policy
        from tpu_cc_manager.obs import OPENMETRICS_CONTENT_TYPE

        return (200, self.metrics.render().encode(),
                OPENMETRICS_CONTENT_TYPE)

    def _timeseries_route(self, query=None):
        # ?metric=<prefix> narrows to one family (ISSUE 15 satellite)
        return self.tsring.route(
            metric_prefix=(query or {}).get("metric"))

    def _incidents_route(self):
        return self.watchdog.route()

    def _fleet_metrics_route(self):
        """The fleet ROLLUP exposition (fleetobs.py): replica series
        merged fleet-wide plus the SLO burn/budget gauges. A separate
        route from /metrics because the rollup re-exposes the agents'
        family names — concatenating it with this controller's own set
        would be exactly the duplicate-declaration bug the validator
        exists to catch."""
        if self.observer is None:
            return 404, b"fleet observer not wired", "text/plain"
        return (200, self.observer.render().encode(),
                "text/plain; version=0.0.4")

    def _report_route(self):
        if self.last_report is None:
            return 503, b"no scan completed yet", "text/plain"
        body = json.dumps(self.last_report, indent=2, sort_keys=True).encode()
        return 200, body, "application/json"

    # -------------------------------------------------------------- watch
    _node_fingerprint = staticmethod(node_report_fingerprint)

    def _on_watch_event(self, etype: str, node: dict) -> None:
        """Feed the planner's feature block — FLEET nodes only. The
        watch streams every cluster node (no server-side selector), but
        the scan lists with ``self.selector``: an unfiltered feed would
        ingest foreign nodes into the encoding (visible in any report
        snapshotted before the next sync() prunes them, and permanently
        sizing the bucket to cluster scale). DELETED always forwards —
        removing an absent row is a no-op."""
        if etype != "DELETED":
            labels = (node.get("metadata") or {}).get("labels") or {}
            if not match_selector(labels, self.selector):
                return
            if self.node_filter is not None and not self.node_filter(node):
                return
        self._encoding.apply_event(etype, node)

    def _wake_scan(self) -> None:
        self._wake.set()

    def _watch_gap(self) -> None:
        """The private watch (re)connected: any deltas between streams
        may have been lost, so the next scan must list-reconcile before
        the planner trusts the feed again (ISSUE 19)."""
        with self._feed_lock:
            self._resync_needed = True

    def _informer_gap_wake(self) -> None:
        """Informer wake doubles as its gap signal: the shared informer
        calls on_wake after every relist/reconnect storm as well as on
        deltas, and a spurious resync costs one fingerprint sweep —
        cheap insurance against a silently stale encoding."""
        with self._feed_lock:
            self._resync_needed = True
        self._wake.set()

    def _on_informer_event(self, etype: str, node: dict) -> None:
        """Shared-informer delta: feed the encoding exactly like the
        private watch did, and wake the scan loop on report-relevant
        changes through the shared fingerprint filter
        (watch.FingerprintWakeFilter). The selector/partition gate
        applies to the wake too — a shared informer delivers the
        WHOLE cluster's events, and at N shards an unscoped wake
        would rescan every shard on every foreign-partition change."""
        self._on_watch_event(etype, node)
        if etype != "DELETED":
            labels = (node.get("metadata") or {}).get("labels") or {}
            if not match_selector(labels, self.selector):
                return
            if self.node_filter is not None and not self.node_filter(node):
                return
        self._informer_wake_filter(etype, node)

    def _watch_loop(self) -> None:
        """Background node watch via :func:`watch.run_node_watch`;
        report-relevant changes wake the scan loop, and every delta
        feeds the planner's feature block so the next scan encodes
        only what moved."""
        with self._feed_lock:
            self._delta_feed_active = True
        try:
            run_node_watch(
                self.kube, self._stop, self._wake.set,
                timeout_s=self.watch_timeout_s,
                backoff_s=self.watch_backoff_s,
                logger=log, who="fleet",
                on_event=self._on_watch_event,
                on_gap=self._watch_gap,
            )
        finally:
            # pump returned (no watch support, or stop): scans fall
            # back to list-reconciling every time
            with self._feed_lock:
                self._delta_feed_active = False

    # ---------------------------------------------------------------- run
    def run(self) -> int:
        self._server.start()
        self.tsring.start()
        # planner compile warmup (ISSUE 7, env-gated — plan.maybe_warmup)
        from tpu_cc_manager import plan

        plan.maybe_warmup(log)
        log.info(
            "fleet controller serving on :%d (selector %r, every %.0fs "
            "+ watch-triggered)",
            self.port, self.selector, self.interval_s,
        )
        if self.informer is not None:
            # shared informer (ISSUE 11): its single watch stream feeds
            # this controller's encoding and wake — no private watch
            self._informer_token = self.informer.subscribe(
                on_event=self._on_informer_event,
                # on_wake fires once per informer relist — exactly the
                # delta-feed gap the sync-skip path must resync over
                on_wake=self._informer_gap_wake,
            )
            with self._feed_lock:
                self._delta_feed_active = True
        else:
            watcher = threading.Thread(
                target=self._watch_loop, name="fleet-watch", daemon=True
            )
            watcher.start()
        if self.leader_elector is not None:
            self.leader_elector.start()
        try:
            while not self._stop.is_set():
                if (self.leader_elector is not None
                        and not self.leader_elector.is_leader):
                    # field contract: every /report carries the digest,
                    # standby included (consumers index it)
                    self.last_report = {"standby": True, "problems": []}
                    self._stop.wait(self.leader_elector.retry_period_s)
                    continue
                try:
                    report = self.scan_once()
                    log.info(
                        "fleet scan: %d nodes, %d divergent, %d failed",
                        report["nodes"], len(report["needs_flip"]),
                        len(report["failed"]),
                    )
                except Exception as e:
                    log.warning("fleet scan failed: %s", e)
                    if not self.healthy:
                        log.error(
                            "%d consecutive scan failures; exiting",
                            self.consecutive_errors,
                        )
                        return 1
                # wake-aware sleep: a watch event ends it early, the
                # interval is the liveness fallback. The min scan gap
                # coalesces event bursts (a 32-node rollout is one or
                # two scans, not 32) and bounds watch-driven scan rate
                if self._wake.wait(self.interval_s):
                    self._wake.clear()
                    # capped at the interval: a wake may only ever make
                    # the next scan SOONER than the tick it replaced
                    self._stop.wait(min(self.min_scan_gap_s,
                                        self.interval_s))
            return 0
        finally:
            self.stop()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()  # unblock a wake-aware sleep immediately
        if self.informer is not None and self._informer_token is not None:
            # a stopped controller must not keep consuming the shared
            # feed (shard demotion constructs a fresh one on re-promote)
            self.informer.unsubscribe(self._informer_token)
            self._informer_token = None
        if self.leader_elector is not None:
            self.leader_elector.stop()  # release: standby takes over now
        self.tsring.stop()
        self._server.stop()
