"""L3 — the per-node agent: watch desired state, reconcile, publish status.

Orchestration mirrors the union of the reference's Go agent (startup +
coalesced reconcile loop, cmd/main.go:119-170) and Python agent
(watch_and_apply, main.py:585-700), with the additions SURVEY.md §7.2
step 5 calls for: metrics around every reconcile, /healthz, and optional
slice coordination.

Error philosophy (reference cmd/main.go:164-167 + main.py:300-307):

- a *reconcile* failure is logged, published as ``cc.mode.state=failed``,
  and the loop continues — the next label event retries;
- a *fatal* condition (mixed-capability node, 10 consecutive watch
  errors) exits the process; the DaemonSet restart policy is the
  recovery mechanism (SURVEY.md §5.3).
"""

from __future__ import annotations

import itertools
import logging
import os
import queue
import threading
import uuid
import time
from contextlib import contextmanager
from typing import Iterator, Optional

from tpu_cc_manager import labels as L
from tpu_cc_manager.config import AgentConfig
from tpu_cc_manager.drain import (
    EVENT_FOR_OUTCOME, NodeFlipTaint, build_drainer, build_node_event,
    post_event_best_effort,
)
from tpu_cc_manager.engine import FatalModeError, ModeEngine
from tpu_cc_manager.flightrec import FlightRecorder, set_recorder
from tpu_cc_manager.k8s.batch import NodePatchBatcher
from tpu_cc_manager.k8s.client import KubeClient
from tpu_cc_manager.modes import STATE_FAILED, InvalidModeError
from tpu_cc_manager.slice_coord import SliceAbortError
from tpu_cc_manager.obs import HealthServer, Metrics, create_readiness_file
from tpu_cc_manager.profiler import SamplingProfiler
from tpu_cc_manager.trace import JsonlSink, Tracer, get_tracer
from tpu_cc_manager.tsring import TimeSeriesRing
from tpu_cc_manager.watchdog import Watchdog
from tpu_cc_manager.watch import FatalWatchError, NodeWatcher, SyncableModeConfig

log = logging.getLogger("tpu-cc-manager.agent")

#: sentinel telling the event-recorder worker to exit
_EVENT_STOP: dict = {}


def with_default(value: Optional[str], default: Optional[str]) -> Optional[str]:
    """Empty/absent label falls back to the default mode (reference
    main.py:691-697; cmd/main.go:158-161). Returns None when neither is
    set, meaning 'nothing to reconcile'."""
    if value:
        return value
    return default or None


class CCManagerAgent:
    #: sentinel for "no evidence build yet this process" — distinct from
    #: None, which means "built unsigned" and must trigger a republish
    #: when a key later appears
    _KEY_UNSET = object()

    def __init__(
        self,
        kube: KubeClient,
        cfg: AgentConfig,
        *,
        metrics: Optional[Metrics] = None,
        slice_coordinator=None,
        backend=None,
        tracer: Optional[Tracer] = None,
    ):
        self.kube = kube
        self.cfg = cfg
        self.metrics = metrics or Metrics()
        # per-agent tracer (not the process-wide one): the multi-node
        # simulation runs many agents in one process, and each agent's
        # spans must land only in its own metrics/sinks. An injected
        # tracer must be dedicated to this agent — sinks are added to it,
        # so sharing one across agents double-counts every span.
        self.tracer = tracer or Tracer()
        self.tracer.add_sink(self.metrics.observe_span)
        if cfg.trace_file:
            self.tracer.add_sink(JsonlSink(cfg.trace_file))
        # the in-process time-series ring (tsring.py, ISSUE 9):
        # periodic snapshots of every registered metric, windowed into
        # rates and quantile estimates on /debug/timeseries and inside
        # flight-recorder dumps
        self.tsring = TimeSeriesRing(self.metrics, name=cfg.node_name)
        # the sampling profiler (profiler.py, ISSUE 15): disarmed and
        # free until an operator arms it (TPU_CC_PROFILER=1) or the
        # watchdog auto-arms a capture burst on an anomaly
        self.profiler = SamplingProfiler(name=cfg.node_name)
        # the per-process black box (flightrec.py, ISSUE 8): recent
        # spans + structured events + host-contention samples, dumped
        # on reconcile failure / SIGTERM / GET /debug/flightrec
        self.flightrec = FlightRecorder(
            name=cfg.node_name, metrics=self.metrics,
            dump_dir=cfg.flightrec_dir, tsring=self.tsring,
            profiler=self.profiler,
        )
        self.tracer.add_sink(self.flightrec.observe_span)
        # the online anomaly watchdog (watchdog.py, ISSUE 15): scores
        # the declared flip/reconcile series on every tsring tick and
        # assembles an incident packet — window stats + exemplar trace
        # ids + a live profile + a throttled black-box dump — served
        # at GET /debug/incidents
        self.watchdog = Watchdog(
            sources=[self.metrics], profiler=self.profiler,
            recorder=self.flightrec, name=cfg.node_name,
        )
        self.tsring.add_listener(self.watchdog.consume)
        # modules that can't take an injected recorder (the batcher's
        # publish-loss accounting) note into the process-wide one:
        # point it at this agent's black box
        set_recorder(self.flightrec)
        self.config_mailbox = SyncableModeConfig(
            on_coalesced=lambda: self.metrics.coalesced_total.inc()
        )
        #: pulsed by every node-watch delta: an in-flight drain wait
        #: (drain.py's pod-wait loops) re-checks on the event instead
        #: of the next poll boundary (ISSUE 14's wake treatment)
        self._drain_wake = threading.Event()
        self.watcher = NodeWatcher(
            kube,
            cfg.node_name,
            self.config_mailbox,
            on_fatal=self._on_fatal_watch,
            on_error=lambda: self.metrics.watch_errors_total.inc(),
            on_event=lambda etype, node: self._drain_wake.set(),
        )
        self.slice_coordinator = slice_coordinator
        if (
            slice_coordinator is not None
            and slice_coordinator.tracer is get_tracer()
        ):
            # coordinator was built without an explicit tracer: adopt it so
            # slice_wait spans land in this agent's trace tree (a tracer
            # injected into the coordinator is left alone)
            slice_coordinator.tracer = self.tracer
        if (
            slice_coordinator is not None
            and slice_coordinator.should_abort is None
        ):
            # an in-flight slice round is superseded the moment a newer
            # desired mode lands in the mailbox — don't stall the round
            # out to its timeout
            slice_coordinator.should_abort = self._superseded_by_pending

        self._backend = backend
        # the write-coalescing I/O layer (k8s.batch, ISSUE 6): evidence
        # and doctor publications defer into it, the taint layer's CAS
        # replaces carry them, the fail-secure state write drains it
        # synchronously, and the idle tick flushes whatever found no
        # carrier. Loss accounting lands in the metrics counters.
        self.batcher = NodePatchBatcher(
            kube, cfg.node_name,
            tracer=self.tracer,
            recorder=self.flightrec,
            on_coalesced=(
                lambda kind: self.metrics
                .publications_coalesced_total.inc(kind)
            ),
            on_retry=(
                lambda kind: self.metrics.publish_retries_total.inc()
            ),
            on_drop=(
                lambda kind: self.metrics
                .publications_dropped_total.inc(kind)
            ),
        )
        self.engine = ModeEngine(
            set_state_label=self._set_state_label,
            drainer=build_drainer(kube, cfg, wake=self._drain_wake),
            evict_components=cfg.evict_components and cfg.drain_strategy != "none",
            backend=backend,
            tracer=self.tracer,
            flip_taint=NodeFlipTaint(
                kube, cfg.node_name,
                batcher=self.batcher,
                node_hint=self.watcher.latest_node,
            ),
            # when the taint-clear replace carries the label, the
            # current-mode gauge still has to move
            notify_state_label=self.metrics.set_current_mode,
            # the long-lived agent keeps the flip executor's worker
            # pool (and through the shared client pool, its warm
            # connections) across reconciles; shutdown() closes it
            persistent_flip_pool=True,
            # host-contention samples bracket every device flip
            recorder=self.flightrec,
        )
        self.health: Optional[HealthServer] = None
        self._fatal: Optional[Exception] = None
        self._stop = threading.Event()
        self.reconcile_count = 0
        self.last_outcome = "none"
        # self-repair state: the last desired mode whose reconcile failed,
        # and the earliest monotonic time a retry may run (VERDICT r1
        # item 8 — heal half-flipped slices without operator relabeling)
        self._repair_mode: Optional[str] = None
        self._repair_due: float = 0.0
        self._repair_failures = 0  # consecutive failures for one mode
        # evidence delivery generations: wanted > published means the
        # newest evidence hasn't landed on the cluster (failed/dropped
        # write) and the idle tick should republish. A stale queued
        # task's success can never mask a newer miss — each task only
        # advances published to ITS OWN generation.
        self._evidence_wanted_gen = 0
        self._evidence_published_gen = 0
        self._evidence_retry_due = 0.0
        self._evidence_key_check_due = 0.0
        #: wall-clock deadline to republish evidence before its
        #: embedded identity token expires (None: no expiring token)
        self._evidence_identity_refresh_at: Optional[float] = None
        #: the key the last evidence build signed with; the idle tick
        #: republishes when the live key differs (the Secret appearing
        #: on a converged, otherwise-idle fleet must re-sign every
        #: node's evidence — no mode flip will ever come to do it).
        #: Sentinel: no build yet this process
        self._evidence_key_used: object = self._KEY_UNSET
        #: the attestation (fake-TPM quote) key of the last build —
        #: same posture-watch treatment as the evidence key
        self._attest_key_used: object = self._KEY_UNSET
        #: the key of the last SUCCESSFULLY PUBLISHED document — the
        #: CCEvidenceResigned Event compares against this, so it fires
        #: only for re-signs that landed, on whichever path landed them
        self._evidence_published_key: object = self._KEY_UNSET
        # periodic doctor self-check throttle (first run shortly after
        # startup, then every doctor_interval_s)
        self._doctor_due = 0.0
        # idle-tick gate drift-heal throttle
        self._gate_reassert_due = 0.0
        # Event-name uniqueness: per-process counter + a startup-unique
        # token, so a restarted agent never collides with the previous
        # process's still-live events (409 AlreadyExists would silently
        # drop them). itertools.count: next() is atomic under the GIL,
        # and events are emitted from two threads (reconcile outcomes,
        # and CCEvidenceResigned from inside the recorder's publish
        # task) — a racing += could mint duplicate names whose second
        # create 409s and is silently dropped.
        self._event_seq = itertools.count(1)
        self._event_token = uuid.uuid4().hex[:8]
        self._event_warned = False
        # Async event delivery (client-go EventRecorder parity): the
        # reconcile loop enqueues, a daemon worker POSTs — an API-server
        # hiccup or slow event write must never stretch reconcile
        # latency. Bounded: overflow drops the event (observability,
        # not correctness).
        self._event_queue: "queue.Queue[dict]" = queue.Queue(maxsize=64)
        self._event_worker: Optional[threading.Thread] = None
        # _event_lock makes close+enqueue atomic: without it a reconcile
        # thread could pass the closed check, lose the CPU, and enqueue
        # behind the stop sentinel into a dead queue
        self._event_lock = threading.Lock()
        self._events_closed = False  # set by shutdown; no enqueues after

    # ------------------------------------------------------------ plumbing
    def _set_state_label(self, value: str) -> None:
        """Publish the observed-state label through the batcher: still
        ONE synchronous, ordered write (fail-secure — a failure raises
        to the reconcile error paths exactly as the plain patch did),
        but the patch also carries any pending evidence/doctor
        publications, so the ordered write doubles as their carrier."""
        self.batcher.write_state_label(value)
        self.metrics.set_current_mode(value)

    def _superseded_by_pending(self, in_flight_mode: str) -> bool:
        """True when the mailbox holds a pending desired value that
        RESOLVES (with_default) to a different mode than the in-flight
        round — a label flap or removal that coalesces back to the same
        effective mode is not a supersession, just churn."""
        has, value = self.config_mailbox.peek_pending()
        if not has:
            return False
        return with_default(value, self.cfg.default_mode) != in_flight_mode

    def _reconcile_current(self, mode: str) -> bool:
        """Reconcile, following supersessions: a superseded round
        immediately re-reconciles the NEWEST desired mode — consuming
        the pending mailbox value if one is still there, or re-running
        the same mode if a flap coalesced back to it (the aborted
        round's ack was retracted, so it must re-run either way). Without
        this, an X->Y->X flap observed mid-round would abort the X round
        and then block on the mailbox forever with X unapplied."""
        # ccaudit: allow-retry-discipline(supersession follow-up, not congestion retry: each turn consumes an already-DELIVERED newer mode from the mailbox (or one label re-read) — pacing it would just hold the freshest desired state unapplied; the stop check bounds it)
        while True:
            ok = self.reconcile(mode)
            if self.last_outcome != "superseded" or self._stop.is_set():
                return ok
            got, value = self.config_mailbox.get(timeout=0)
            if not got:
                # nothing pending — either a flap coalesced back to this
                # mode, or the watcher isn't feeding the mailbox yet (the
                # STARTUP reconcile runs before watcher.start()). Re-read
                # the label directly: re-running the old mode against a
                # changed label would supersede-abort forever.
                try:
                    node = self.kube.get_node(self.cfg.node_name)
                    value = (node["metadata"].get("labels") or {}).get(
                        L.CC_MODE_LABEL)
                except Exception:
                    log.warning("desired-label re-read failed; retrying "
                                "the superseded mode", exc_info=True)
                    continue
            new_mode = with_default(value, self.cfg.default_mode)
            if new_mode is None:
                # desired mode withdrawn entirely (label removed, no
                # default): the superseded round stays unapplied by design
                self._disarm_repair()
                return ok
            mode = new_mode

    def _publish_evidence(self) -> None:
        """Best-effort per-flip attestation evidence annotation (see
        tpu_cc_manager.evidence): published after every successful
        reconcile so the fleet controller can audit evidence-vs-label
        consistency. Delivered through the COALESCING publish core
        (k8s.batch): the document defers into the batcher, rides the
        next node write (usually the next flip's taint set) or the idle
        tick's flush, and only the newest generation is ever sent — an
        API-server hiccup or slow annotation write never stretches
        reconcile latency, superseded generations are counted
        (publications_coalesced_total), and a publish that exhausts the
        flush retry budget is re-deferred from the idle tick because
        published < wanted. Staleness in between is visible, not
        silent — the fleet audit flags it."""
        if not self.cfg.emit_evidence:
            return
        import json as _json

        from tpu_cc_manager import device as devlayer
        from tpu_cc_manager.evidence import build_evidence, evidence_key

        # this publication's generation: anything that keeps it from
        # landing (build failure, queue overflow, write failure) leaves
        # published < wanted, and the idle tick republishes — stale
        # on-cluster evidence reads as a label-vs-device contradiction
        # to auditors, and the next label change may never come
        self._evidence_wanted_gen += 1
        gen = self._evidence_wanted_gen

        # build the document SYNCHRONOUSLY (cheap local reads): a
        # drain-time build could race the next flip and attest a torn
        # mid-transition state under the old reconcile's banner. Only
        # the API write is deferred.
        try:
            with self.tracer.span("evidence_build"):
                from tpu_cc_manager.attest import tpm_key

                backend = self._backend or devlayer.get_backend()
                key = evidence_key()
                # snapshot BEFORE the build: a rotation landing between
                # this read and the quote's own would then record the
                # OLD key against a new-key quote — one harmless extra
                # republish on the next idle tick; reading AFTER would
                # record the NEW key against an old-key quote and
                # suppress the re-sign forever
                akey = tpm_key()
                doc = build_evidence(self.cfg.node_name, backend,
                                     key=key)
                payload = _json.dumps(doc, sort_keys=True,
                                      separators=(",", ":"))
            # recorded at build time (not publish time): what matters
            # for the idle tick's re-sign check is the posture of the
            # newest document headed for the cluster. The attestation
            # key rides along: a rotated TPM key must re-sign quotes
            # the same way a rotated pool key re-signs digests.
            self._evidence_key_used = key
            self._attest_key_used = akey
            self._evidence_identity_refresh_at = (
                self._evidence_refresh_deadline(doc)
            )
        except Exception:
            log.warning("evidence build failed; will retry", exc_info=True)
            return

        def landed(published_gen: int) -> None:
            # runs on whichever thread's write carried the document
            # (taint CAS, state patch, or idle-tick flush). Advance
            # published only to THIS publication's generation — a stale
            # write's success must not mask a newer miss.
            self._evidence_published_gen = max(
                self._evidence_published_gen, published_gen
            )
            # rotation progress is fleet-visible only for documents
            # that actually LANDED: compare signing posture against
            # the last successfully published one, so the Event is
            # truthful (never claims a failed publish) and fires on
            # whichever path re-signed — the idle-tick posture
            # check, the dropped-publish retry, or a plain flip
            prev = self._evidence_published_key
            self._evidence_published_key = key
            if prev is not self._KEY_UNSET and key != prev:
                self._emit_node_event(
                    "CCEvidenceResigned",
                    "evidence key posture changed (Secret "
                    "appeared/rotated/removed); re-signed "
                    "attestation evidence with the current key",
                )

        self.batcher.defer(
            "evidence",
            annotations={L.EVIDENCE_ANNOTATION: payload},
            gen=gen,
            on_published=landed,
        )

    def _evidence_refresh_deadline(self, doc: dict) -> Optional[float]:
        """The earlier of the identity-token and attestation-token
        refresh deadlines: either aging out makes the idle tick
        republish. Fake-tpm quotes carry no expiry (their freshness is
        the key posture check)."""
        from tpu_cc_manager.attest import quote_refresh_deadline

        deadlines = [
            d for d in (
                self._identity_refresh_deadline(doc),
                quote_refresh_deadline(doc),
            ) if d is not None
        ]
        return min(deadlines) if deadlines else None

    def _identity_refresh_deadline(self, doc: dict) -> Optional[float]:
        """Wall-clock time at which the evidence should be republished
        because its embedded identity token nears expiry (verifiers
        class an expired token with 'missing'; an idle node must
        refresh BEFORE that, since no flip will come to do it). None
        when no identity is expected or the token carries no expiry."""
        try:
            token = (doc.get("identity") or {}).get("token")
            if not token:
                from tpu_cc_manager.identity import get_identity_provider

                if get_identity_provider() is not None:
                    # a provider is configured but the fetch failed
                    # (metadata blip): RETRY from the idle tick — one
                    # blip must not strip identity from this node's
                    # evidence for the rest of the process lifetime
                    return time.time() + 2 * (
                        self.cfg.repair_interval_s or 30.0
                    )
                return None
            from tpu_cc_manager.identity import token_claims

            _, claims = token_claims(token)
            exp = claims.get("exp")
            iat = claims.get("iat", time.time())
            if not isinstance(exp, (int, float)):
                return None
            # refresh when REPUBLISH_MARGIN of the lifetime remains
            # (see identity.py for why it sits inside the token cache's
            # serve margin) — comfortably ahead of the verifier-visible
            # expiry (~12 min for 1 h GCE tokens)
            from tpu_cc_manager.identity import REPUBLISH_MARGIN

            return float(exp) - REPUBLISH_MARGIN * max(
                float(exp) - float(iat), 0.0
            )
        except Exception:
            log.debug("evidence republish deadline unparseable; "
                      "relying on the repair-interval fallback",
                      exc_info=True)
            return None

    def _on_fatal_watch(self, exc: Exception) -> None:
        self._fatal = exc
        self._stop.set()
        self.config_mailbox.close()

    def _prime_with_retry(self) -> Optional[str]:
        """Initial node read with the watch loop's backoff/fatal policy
        (reference main.py:664-689 applied to startup)."""
        from tpu_cc_manager.k8s.client import ApiException

        attempts = 0
        while True:
            try:
                return self.watcher.prime()
            except ApiException as e:
                attempts += 1
                self.metrics.watch_errors_total.inc()
                if attempts >= self.watcher.max_consecutive_errors:
                    raise FatalWatchError(
                        f"{attempts} consecutive failures reading node "
                        f"{self.cfg.node_name} at startup; last: {e}"
                    ) from e
                log.warning(
                    "startup node read failed (%d): %s; retrying in %.1fs",
                    attempts, e, self.watcher.backoff_s,
                )
                # event wait, not a fixed sleep: shutdown (the only
                # wake source at startup) cuts the backoff short
                if self._stop.wait(self.watcher.backoff_s):
                    return None

    # ----------------------------------------------------------- reconcile
    @contextmanager
    def _reconcile_span(self, raw_mode: str) -> Iterator[object]:
        """The reconcile root span, seated under the desired-writer's
        cross-process trace context when the watched node carries one
        (the cc.trace annotation rides the same write — and therefore
        the same watch event — as the desired label): ONE trace then
        spans controller desired-write → watch delivery → drain →
        flip phases → state publish. A missing/garbled annotation
        degrades to the historical local root."""
        with self.tracer.adopt_remote(self.watcher.latest_trace_context()):
            with self.tracer.span("reconcile", mode=raw_mode) as root:
                yield root

    def reconcile(self, raw_mode: str) -> bool:
        """One mode application, instrumented. Never raises except
        FatalModeError."""
        start = time.monotonic()
        outcome = "error"
        try:
            return self._reconcile_traced(raw_mode, start)
        finally:
            # OUTSIDE the span context: the root reconcile span has hit
            # the sinks (flightrec's ring included) by now, so a
            # failure dump contains the very reconcile it documents —
            # outcome attr, duration, and adopted cross-process parent
            outcome = self.last_outcome or outcome
            self.flightrec.note(
                "reconcile", mode=raw_mode, outcome=outcome,
                dur_s=round(time.monotonic() - start, 4),
            )
            if outcome in ("failure", "error", "slice_abort", "fatal"):
                # the black box leaves the scene of the crash: recent
                # spans, events, host samples, and a metrics snapshot
                # land in one JSON artifact (throttled — a flapping
                # device can't fill the disk)
                self.flightrec.maybe_dump(f"reconcile_{outcome}")

    def _reconcile_traced(self, raw_mode: str, start: float) -> bool:
        outcome = "error"
        with self._reconcile_span(raw_mode) as root_span:
            try:
                if self.slice_coordinator is not None:
                    ok = self.slice_coordinator.apply_slice_coherent(
                        raw_mode, self.engine
                    )
                else:
                    ok = self.engine.set_mode(raw_mode)
                outcome = "success" if ok else "failure"
                return ok
            except InvalidModeError as e:
                # bad label value: report, keep serving (the operator may
                # fix it)
                log.error("rejecting desired mode: %s", e)
                try:
                    self._set_state_label(STATE_FAILED)
                except Exception:
                    log.exception("failed to publish failed state")
                outcome = "invalid"
                return False
            except SliceAbortError as e:
                # the slice never agreed; local devices untouched
                log.error("slice coordination aborted: %s", e)
                if e.shutting_down:
                    # termination artifact, not a real failure: leave the
                    # durable state label alone
                    outcome = "shutdown"
                    return False
                if e.superseded:
                    # the operator changed the desired mode mid-round: not
                    # a failure — the mailbox already holds the new mode
                    # and the main loop reconciles it immediately. No
                    # failed label, no Warning event, no repair arming.
                    outcome = "superseded"
                    return False
                try:
                    self._set_state_label(STATE_FAILED)
                except Exception:
                    log.exception("failed to publish failed state")
                outcome = "slice_abort"
                return False
            except FatalModeError:
                outcome = "fatal"
                raise
            except Exception:
                log.exception("reconcile crashed")
                try:
                    self._set_state_label(STATE_FAILED)
                except Exception:
                    log.exception("failed to publish failed state")
                return False
            finally:
                dur = time.monotonic() - start
                self.last_outcome = outcome
                if outcome == "success":
                    self._publish_evidence()
                self._arm_repair(raw_mode, outcome)
                self._emit_reconcile_event(raw_mode, outcome, dur)
                root_span.attrs["outcome"] = outcome
                # the reconcile's trace id rides as the latency
                # bucket's exemplar (ISSUE 15): a slow bucket on
                # /metrics points at THIS reconcile's trace
                self.metrics.reconcile_duration.observe(
                    dur, trace_id=root_span.trace_id)
                self.metrics.reconciles_total.inc(outcome)
                self.reconcile_count += 1
                log.info("reconcile finished: %s in %.3fs", outcome, dur)

    def _publish_doctor(self) -> None:
        """Periodic trust-surface self-check (tpu_cc_manager.doctor)
        published as the cc.doctor annotation for the fleet controller
        to aggregate. Runs on the idle tick, so it must never raise and
        never block the mailbox for long; the report build is local
        reads plus one get_node, and the verdict write defers into the
        coalescing batcher like evidence — it rides the next node write
        or flush, and only the newest verdict is ever sent."""
        import json as _json

        from tpu_cc_manager import device as devlayer
        from tpu_cc_manager.doctor import run_doctor

        try:
            with self.tracer.span("doctor"):
                backend = self._backend or devlayer.get_backend()
                report = run_doctor(
                    kube=self.kube, node_name=self.cfg.node_name,
                    backend=backend,
                )
            summary = {
                "ok": report["ok"],
                "fail": sorted({c["name"] for c in report["checks"]
                                if c["severity"] == "fail"}),
                "warn": sorted({c["name"] for c in report["checks"]
                                if c["severity"] == "warn"}),
                "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            }
            payload = _json.dumps(summary, sort_keys=True,
                                  separators=(",", ":"))
        except Exception:
            log.warning("doctor self-check failed", exc_info=True)
            return
        if not report["ok"]:
            log.warning("doctor self-check failing: %s", summary["fail"])

        ok_label = "true" if report["ok"] else "false"
        # annotation = detail, label = selectable mirror; one deferred
        # publication so both always land in the same write
        self.batcher.defer(
            "doctor",
            labels={L.DOCTOR_OK_LABEL: ok_label},
            annotations={L.DOCTOR_ANNOTATION: payload},
        )

    def _emit_reconcile_event(self, mode: str, outcome: str, dur: float) -> None:
        """Best-effort core/v1 Event so `kubectl describe node` carries
        the mode-flip history (the reference records outcomes only in a
        label + pod logs). Never interferes with the reconcile result."""
        hit = EVENT_FOR_OUTCOME.get(outcome)
        if hit is None:
            return
        reason, etype = hit
        self._emit_node_event(
            reason,
            f"cc mode reconcile to '{mode}': {outcome} in {dur:.2f}s",
            etype, infix="cc-reconcile",
        )

    def _emit_node_event(self, reason: str, message: str,
                         etype: str = "Normal", *,
                         infix: str = "cc-maint") -> None:
        """Best-effort node Event through the async recorder — reconcile
        outcomes and trust-surface maintenance (key rotation) both show
        in `kubectl describe node`. ``infix`` keeps the two name
        spaces distinct."""
        if not self.cfg.emit_events:
            return
        seq = next(self._event_seq)
        event = build_node_event(
            self.cfg.node_name, reason, message, etype,
            name=(
                f"{self.cfg.node_name}.{infix}."
                f"{self._event_token}.{seq}"
            ),
        )
        if self._enqueue_recorder_item(event) == "full":
            self.metrics.events_dropped_total.inc()
            log.debug("event queue full; dropping %s", reason)

    def _enqueue_recorder_item(self, item) -> str:
        """Hand an Event dict or a callable task to the async recorder
        worker. Returns "ok", "closed" (shutting down — a routine
        non-delivery, not a drop), or "full" (bounded-queue overflow —
        the caller accounts for the drop)."""
        with self._event_lock:
            if self._events_closed:
                return "closed"  # would strand behind the STOP sentinel
            if self._event_worker is None or not self._event_worker.is_alive():
                self._event_worker = threading.Thread(
                    target=self._event_loop, daemon=True,
                    name="cc-event-recorder",
                )
                self._event_worker.start()
            try:
                self._event_queue.put_nowait(item)
                return "ok"
            except queue.Full:
                return "full"

    def _event_loop(self) -> None:
        """Daemon worker draining the recorder queue (Event dicts and
        callable tasks such as evidence publication). One failed POST
        must never affect a reconcile. A clientset without Events support
        (501) stays at debug; anything else (403 RBAC missing, 400
        validation) warns once so a misconfigured deployment doesn't
        silently lose the whole feature."""
        while True:
            # ccaudit: allow-stop-aware-wait(the _EVENT_STOP sentinel IS the wakeup: stop() enqueues it, so this blocking get returns immediately on shutdown — a timeout would only add idle churn to a daemon drain thread)
            event = self._event_queue.get()
            try:
                if event is _EVENT_STOP:
                    return
                if callable(event):
                    try:
                        event()
                    except Exception:
                        log.exception("async recorder task failed")
                    continue
                delivered, warned = post_event_best_effort(
                    self.kube, event, warned_before=self._event_warned
                )
                if delivered:
                    self.metrics.events_emitted_total.inc()
                if warned:
                    self._event_warned = True
            finally:
                self._event_queue.task_done()

    def flush_events(self, timeout: float = 5.0) -> bool:
        """Block until queued events AND deferred publications are
        delivered (tests + shutdown). The batcher flush is synchronous;
        a failed flush stays pending (retry machinery owns it) and does
        not fail this wait — same contract the recorder queue had."""
        self.batcher.flush()
        if self._event_worker is None or not self._event_worker.is_alive():
            return True
        # queue-join with a deadline: ride the queue's own
        # all_tasks_done condition (task_done() notifies it) instead
        # of spinning a 10ms poll against the worker's progress
        deadline = time.monotonic() + timeout
        with self._event_queue.all_tasks_done:
            while self._event_queue.unfinished_tasks:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._event_queue.all_tasks_done.wait(remaining)
        return True

    # -------------------------------------------------------------- repair
    def _disarm_repair(self) -> None:
        self._repair_mode = None
        self._repair_failures = 0

    def _arm_repair(self, mode: str, outcome: str) -> None:
        """Arm (or disarm) the self-repair retry; runs at the end of
        every reconcile.

        Only *retryable* failures arm it: an invalid label value fails
        deterministically until the operator fixes the label, and that
        label change triggers its own reconcile — retrying would just
        churn the API server. Consecutive failures for the same mode
        back off exponentially (capped at 32x the base interval): a
        persistently stuck slice member would otherwise cost a full
        commit-timeout wait every repair_interval_s, starving the event
        loop and hammering the API server with the slice wait's 1 Hz
        node lists."""
        if (
            not self.cfg.repair_interval_s
            or self._stop.is_set()
            or outcome not in ("failure", "slice_abort", "error")
        ):
            self._disarm_repair()
            return
        if mode != self._repair_mode:
            self._repair_failures = 0
        self._repair_mode = mode
        self._repair_failures += 1
        delay = self.cfg.repair_interval_s * min(
            2 ** (self._repair_failures - 1), 32
        )
        self._repair_due = time.monotonic() + delay
        # fast-track the next doctor self-check: a failed flip changes
        # the node's trust surfaces (fail-secure gate lock, statefile
        # state) and the fleet should see the failing verdict within
        # seconds, not after the remaining doctor interval
        self._doctor_due = 0.0

    def _maybe_repair(self) -> None:
        """Idle-tick self-repair: retry the last failed reconcile.

        The reference retries only on the next label *event*
        (cmd/main.go:164-167) — but a half-flipped slice never produces
        one: the desired label is already correct, only this node's
        device state (and ``cc.mode.state=failed``) lag. Retrying here
        re-enters the slice protocol, observes the still-actionable
        quorum commit on the anchor, and converges the laggard without
        any operator relabeling (VERDICT r1 item 8). Plain (non-slice)
        device faults heal the same way.
        """
        now = time.monotonic()
        # deliver deferred publications that found no carrier write
        # FIRST: the doctor check below reads the on-cluster evidence,
        # and the retry branch must not mistake "awaiting its flush"
        # for "failed"
        self.batcher.maybe_flush()
        if (self.cfg.emit_evidence
                and self._evidence_published_gen < self._evidence_wanted_gen
                and not self.batcher.has_pending("evidence")
                and now >= self._evidence_retry_due):
            # a dropped/failed evidence publish left stale on-cluster
            # evidence; republish from current device state (throttled —
            # a persistently failing API must not be hammered every tick)
            self._evidence_retry_due = now + (
                self.cfg.repair_interval_s or 30.0
            )
            self._publish_evidence()
        elif (self.cfg.emit_evidence
                and self._evidence_key_used is not self._KEY_UNSET
                and now >= self._evidence_key_check_due):
            # key-posture change: the evidence-key Secret appeared (or
            # rotated/vanished) on an idle, converged node. No mode flip
            # will ever come to re-sign the annotation, and a keyed
            # verifier would read the stale unsigned document as an
            # 'unsigned' fleet problem telling the operator to apply a
            # fix they already applied — so the agent re-signs here.
            # Advanced on EVERY check, not just on change: idle ticks
            # run ~1/s and the Secret file must not be opened that often
            from tpu_cc_manager.attest import tpm_key
            from tpu_cc_manager.evidence import evidence_key

            self._evidence_key_check_due = now + (
                self.cfg.repair_interval_s or 30.0
            )
            if (evidence_key() != self._evidence_key_used
                    or tpm_key() != self._attest_key_used):
                log.info(
                    "evidence key posture changed; re-signing evidence"
                )
                # the CCEvidenceResigned Event rides the publish task:
                # it fires only once the re-signed document LANDS
                self._publish_evidence()
            elif (self._evidence_identity_refresh_at is not None
                    and time.time()
                    >= self._evidence_identity_refresh_at):
                # the embedded identity token nears expiry and no flip
                # is coming: republish so verifiers never see this
                # idle node's identity age out into 'expired'
                log.info("identity token nearing expiry; refreshing "
                         "evidence")
                self._publish_evidence()
        # heal gate-perms drift on idle nodes (same cadence as repair;
        # local chmods only, no cluster traffic)
        if self.cfg.repair_interval_s and now >= self._gate_reassert_due:
            self._gate_reassert_due = now + self.cfg.repair_interval_s
            self.engine.reassert_gate()
        # periodic doctor self-check published as the cc.doctor
        # annotation: keeps the fleet controller's trust-surface
        # aggregation fresh without anyone running doctor by hand
        if self.cfg.doctor_interval_s and now >= self._doctor_due:
            self._doctor_due = now + self.cfg.doctor_interval_s
            self._publish_doctor()
        if self._repair_mode is None or time.monotonic() < self._repair_due:
            return
        mode = self._repair_mode
        log.info("self-repair: retrying failed reconcile to %r", mode)
        self.metrics.repairs_total.inc()
        self.reconcile(mode)  # re-arms (with backoff) or disarms itself

    # ---------------------------------------------------------------- run
    def run(self, max_reconciles: Optional[int] = None) -> int:
        """Run the agent. Returns a process exit code. ``max_reconciles``
        bounds loop iterations for tests/bench (None = forever)."""
        cfg = self.cfg
        if self.slice_coordinator is not None:
            self.slice_coordinator.start()
        if cfg.health_port:  # 0 disables (SURVEY.md §5.6 table)
            try:
                self.health = HealthServer(
                    self.metrics, port=cfg.health_port,
                    tracer=self.tracer, flightrec=self.flightrec,
                    tsring=self.tsring, watchdog=self.watchdog,
                ).start()
            except OSError as e:
                log.warning("health server disabled: %s", e)
        self.tsring.start()
        if os.environ.get("TPU_CC_PROFILER", "").lower() in (
                "1", "true", "yes"):
            # operator opt-in continuous sampling (the on-demand half
            # of ISSUE 15; the watchdog's capture bursts need no arm)
            self.profiler.arm()

        try:
            # initial read + reconcile (reference cmd/main.go:131-149,
            # main.py:614-617); transient API errors at startup get the
            # same backoff treatment as the watch loop
            initial = self._prime_with_retry()
            mode = with_default(initial, cfg.default_mode)
            # a prime cut short by shutdown returns None — that is NOT
            # "no label, apply the default": a stopping agent must not
            # drain and flip the node toward the default on its way out
            if mode is not None and not self._stop.is_set():
                ok = self._reconcile_current(mode)
                if (not ok and initial is None
                        and self.last_outcome not in ("superseded",
                                                      "shutdown")):
                    # startup default-apply failure is fatal in the Go agent
                    # (cmd/main.go:141-145); a superseded or shutting-down
                    # startup round is not a failure
                    log.error("initial default-mode apply failed; exiting")
                    return 1
            # signal readiness only after the initial reconcile
            # (reference main.py:617, scripts/cc-manager.sh:536)
            create_readiness_file(cfg.readiness_file)
            if self.health:
                self.health.ready = True

            self.watcher.start()
            while not self._stop.is_set():
                got, value = self.config_mailbox.get(timeout=1.0)
                if not got:
                    if max_reconciles is not None and self.reconcile_count >= max_reconciles:
                        break
                    self._maybe_repair()
                    continue
                if self._stop.is_set():
                    break
                mode = with_default(value, cfg.default_mode)
                if mode is None:
                    # desired mode withdrawn (label removed, no default):
                    # a pending repair must not re-apply the stale mode
                    self._disarm_repair()
                    continue
                # failure: log + continue (go :164-167); supersession:
                # retried inside with the newest mode
                self._reconcile_current(mode)
                if max_reconciles is not None and self.reconcile_count >= max_reconciles:
                    break
            if self._fatal is not None:
                log.error("agent exiting on fatal error: %s", self._fatal)
                return 1
            return 0
        except FatalModeError as e:
            log.error("fatal: %s", e)
            return 1
        except FatalWatchError as e:
            log.error("fatal: %s", e)
            return 1
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        self._stop.set()
        # close the recorder first (under the lock, so a reconcile
        # finishing concurrently either enqueued before the close or
        # skips emission entirely — nothing can land behind STOP), then
        # deliver what's queued and stop the worker
        with self._event_lock:
            self._events_closed = True
        self.flush_events(timeout=2.0)
        if self._event_worker is not None and self._event_worker.is_alive():
            try:
                self._event_queue.put_nowait(_EVENT_STOP)
            except queue.Full:
                pass
        if self.slice_coordinator is not None:
            self.slice_coordinator.stop()
        self.tsring.stop()
        self.profiler.disarm()
        self.watcher.stop()
        # best-effort final flush of deferred publications, then release
        # the engine's persistent flip-executor threads
        try:
            self.batcher.close()
        except Exception:
            log.warning("final publish flush failed", exc_info=True)
        self.engine.close()
        if self.health:
            self.health.live = False
            self.health.stop()
            self.health = None
