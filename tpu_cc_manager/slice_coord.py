"""Slice-coherent mode flips — the capability the reference never needed.

A multi-host TPU slice (e.g. v5p-128 = 16 hosts) is one ICI/attestation
domain: flipping CC mode on some member nodes but not others would leave
the slice half-protected, which is worse than either uniform state. The
reference's agents are fully node-independent (SURVEY.md §2.3); this
module adds the per-slice choreography SURVEY.md §7.2 step 7 calls for,
using only the coordination fabric the architecture already has — the
Kubernetes API server — so no new communication backend is introduced.

Protocol (all state in node labels/annotations, so it survives agent
restarts and is kubectl-observable):

- **Membership**: nodes of one slice share ``tpu.google.com/cc.slice``
  (set by the provisioner / GKE node-pool labels).
- **Liveness**: each agent heartbeats ``cc.slice.hb=<unix-ts>`` on its own
  node. A member is *alive* if its heartbeat is fresher than HB_TTL_S.
- **Leadership**: the alive member with the lexicographically smallest
  node name is the leader. Deterministic — every member computes the same
  answer from the same node list; no election messages. If the leader
  dies its heartbeat stales out and the next member takes over.
- **Commit fencing (CAS)**: commits live on the *anchor* node — the
  lexicographically smallest member of the slice, alive or not (the node
  *object* always exists even when its agent is down). The leader writes
  the commit with ``replace_node`` preconditioned on the anchor's
  resourceVersion (Kubernetes optimistic concurrency, the mechanism
  client-go's leader-election leases use). Two members that both believe
  they are leader during a heartbeat-staleness window therefore race a
  compare-and-swap on one object: exactly one write per epoch wins, the
  loser gets 409 Conflict, re-reads, and finds the round already
  committed. Members always *read* commits from the anchor, so divergent
  leaders can never produce divergent observed commits. The winning
  leader also records ``cc.slice.leader=<name>`` and
  ``cc.slice.epoch=<epoch>`` on the anchor for auditability.
- **Epochs**: rounds are ordered by the cluster's resourceVersion, which
  is globally monotone (etcd revision). The leader stamps each commit
  with the highest member rv it observed; members remember the epoch of
  the last commit they consumed (``cc.slice.done=<mode>:<epoch>``, on
  their own node, durable across restarts). A commit is actionable only
  if its epoch is *strictly greater* than the member's done epoch —
  stale commits left over from old rounds (e.g. on a node that lost and
  later regained leadership) can never trigger a flip.
- **Two-phase flip**:

  1. every member publishes ``cc.slice.ack=<mode>`` on its own node
     ("I see the new desired mode and am ready to flip");
  2. the leader, once ALL alive members ack the same mode and not all of
     them have already completed it, publishes
     ``cc.slice.commit=<mode>:<epoch>`` on the anchor node via CAS;
  3. members flip locally only after observing a commit whose mode
     equals the mode they acked and whose epoch is newer than their done
     epoch; then they record ``cc.slice.done``.

  A member that aborts (timeout, shutdown, API errors) **retracts its
  ack** so the leader stops counting it. The retraction is best-effort:
  if the leader read the ack in the same instant, the rest of the slice
  may proceed while the aborted member reports ``cc.mode.state=failed``
  — a visibly mixed slice (the fleet planner's ``half_flipped_slices``
  audit catches exactly this), never a silently mixed one. Full
  atomicity under arbitrary timing is the two-generals problem; the
  protocol guarantees no member *flips* without a quorum commit, and
  every divergence is published. Divergences also *heal*: the agent's
  self-repair loop (CCManagerAgent._maybe_repair) retries the failed
  reconcile, and because the quorum commit on the anchor stays
  actionable until the laggard records ``done``, the retry converges the
  slice without a new quorum round or any operator relabeling.

Divergent per-slice policies (BASELINE config 5) fall out naturally:
coordination is scoped to one slice id, so two slices of one pool can
hold different modes.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.client import ApiException, ConflictError, KubeClient
from tpu_cc_manager.trace import Tracer, get_tracer

log = logging.getLogger("tpu-cc-manager.slice")

#: Heartbeat refresh period and liveness TTL.
HB_PERIOD_S = 10.0
HB_TTL_S = 45.0
#: How long a member waits for the slice to agree before giving up.
COMMIT_TIMEOUT_S = 600.0
POLL_S = 1.0

# local aliases: the protocol strings live in labels.py with the rest of
# the cluster-visible surface (ccaudit's label-literal rule enforces it)
HB_ANNOTATION = L.SLICE_HB_ANNOTATION
DONE_ANNOTATION = L.SLICE_DONE_ANNOTATION


class SliceAbortError(Exception):
    """The slice round did not reach a commit; the local flip was NOT
    attempted. The agent publishes the failed state and keeps serving —
    except when ``shutting_down`` is set (an artifact of agent
    termination) or ``superseded`` is set (the operator changed the
    desired mode mid-round; the NEW mode is about to reconcile), in
    which cases no failure is published."""

    def __init__(self, msg: str, *, shutting_down: bool = False,
                 superseded: bool = False):
        super().__init__(msg)
        self.shutting_down = shutting_down
        self.superseded = superseded


def _parse_stamp(raw: Optional[str]) -> Tuple[Optional[str], int]:
    """'mode:epoch' -> (mode, epoch); absent/garbage -> (None, -1)."""
    if not raw or ":" not in raw:
        return None, -1
    mode, _, epoch = raw.rpartition(":")
    try:
        return mode, int(epoch)
    except ValueError:
        return None, -1


class SliceCoordinator:
    def __init__(
        self,
        kube: KubeClient,
        node_name: str,
        *,
        hb_period_s: float = HB_PERIOD_S,
        hb_ttl_s: float = HB_TTL_S,
        commit_timeout_s: Optional[float] = None,
        poll_s: float = POLL_S,
        clock=time.time,
        tracer: Optional[Tracer] = None,
        should_abort=None,
    ):
        self.kube = kube
        self.node_name = node_name
        self.tracer = tracer or get_tracer()
        self.hb_period_s = hb_period_s
        self.hb_ttl_s = hb_ttl_s
        # env parsing/validation lives in config.py
        # (TPU_CC_SLICE_COMMIT_TIMEOUT_S -> cfg.slice_commit_timeout_s,
        # threaded in by __main__) — None here just means the default
        self.commit_timeout_s = (
            COMMIT_TIMEOUT_S if commit_timeout_s is None
            else commit_timeout_s
        )
        self.poll_s = poll_s
        self.clock = clock
        #: Optional callable polled during the commit wait with the
        #: in-flight mode: True means a newer desired mode has arrived
        #: that RESOLVES to a different mode, so this round is superseded
        #: (the agent wires it to a with_default-aware mailbox peek —
        #: a label flap that coalesces back to the same effective mode
        #: must not abort the round).
        self.should_abort = should_abort
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- membership
    def slice_id(self) -> Optional[str]:
        node = self.kube.get_node(self.node_name)
        return node["metadata"].get("labels", {}).get(L.TPU_SLICE_LABEL)

    def members(self, slice_id: str) -> List[dict]:
        return sorted(
            self.kube.list_nodes(f"{L.TPU_SLICE_LABEL}={slice_id}"),
            key=lambda n: n["metadata"]["name"],
        )

    def _alive(self, nodes: List[dict]) -> List[dict]:
        now = self.clock()
        alive = []
        for n in nodes:
            raw = n["metadata"].get("annotations", {}).get(HB_ANNOTATION)
            try:
                fresh = raw is not None and now - float(raw) <= self.hb_ttl_s
            except ValueError:
                fresh = False
            # our own row is alive by definition (we're executing)
            if fresh or n["metadata"]["name"] == self.node_name:
                alive.append(n)
        return alive

    # ----------------------------------------------------------- heartbeat
    def heartbeat_once(self) -> None:
        self.kube.set_node_annotations(
            self.node_name, {HB_ANNOTATION: str(self.clock())}
        )

    def start(self) -> "SliceCoordinator":
        """Run the background heartbeat (agent lifetime)."""

        def loop():
            while not self._stop.is_set():
                try:
                    self.heartbeat_once()
                except ApiException as e:
                    log.warning("slice heartbeat failed: %s", e)
                self._stop.wait(self.hb_period_s)

        self._hb_thread = threading.Thread(
            target=loop, name="slice-heartbeat", daemon=True
        )
        self._hb_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._hb_thread:
            self._hb_thread.join(timeout=5)

    # ------------------------------------------------------------ protocol
    def _annotate_self(self, key: str, value: Optional[str]) -> None:
        self.kube.set_node_annotations(self.node_name, {key: value})

    @staticmethod
    def _ann(node: dict, key: str) -> Optional[str]:
        return node["metadata"].get("annotations", {}).get(key)

    def _retract_ack(self) -> None:
        try:
            self._annotate_self(L.SLICE_ACK_ANNOTATION, None)
        except ApiException as e:
            log.warning("could not retract slice ack: %s", e)

    def apply_slice_coherent(self, raw_mode: str, engine) -> bool:
        """Run the 2-phase protocol around ``engine.set_mode``.

        Falls back to a plain local flip when the node is not part of a
        multi-host slice. Raises SliceAbortError when the round never
        reached a commit (the local device state was not touched).
        """
        # validate BEFORE any ack is published: a typo'd mode must be
        # the instant InvalidModeError rejection every other path gives
        # (engine.set_mode would catch it, but only after this member
        # acked garbage to the slice and waited out the whole quorum
        # timeout on peers who will never ack it)
        from tpu_cc_manager.modes import parse_mode

        parse_mode(raw_mode)
        slice_id = self.slice_id()
        if not slice_id:
            return engine.set_mode(raw_mode)
        members = self.members(slice_id)
        if len(members) <= 1:
            return engine.set_mode(raw_mode)

        log.info(
            "slice %s: coordinating flip to %r across %d members",
            slice_id, raw_mode, len(members),
        )
        me = next(
            n for n in members if n["metadata"]["name"] == self.node_name
        )
        my_done_mode, my_done_epoch = _parse_stamp(
            self._ann(me, DONE_ANNOTATION)
        )
        if my_done_mode == raw_mode:
            # this member already completed a round for exactly this mode
            # (routine agent restart re-reconciling the unchanged label):
            # no quorum needed — the local engine call is idempotent and
            # republishes the state label (engine fast path).
            log.info(
                "slice %s: mode %r already completed (epoch %d); "
                "re-applying locally without coordination",
                slice_id, raw_mode, my_done_epoch,
            )
            return engine.set_mode(raw_mode)

        try:
            self.heartbeat_once()
            self._annotate_self(L.SLICE_ACK_ANNOTATION, raw_mode)
        except ApiException as e:
            raise SliceAbortError(f"could not publish slice ack: {e}") from e

        deadline = time.monotonic() + self.commit_timeout_s
        last_hb = self.clock()
        # refresh the heartbeat well inside the TTL even when start()'s
        # background thread isn't running, without PATCHing every poll
        hb_refresh_s = min(self.hb_period_s, self.hb_ttl_s / 3.0)
        commit_epoch: Optional[int] = None
        with self.tracer.span(
            "slice_wait", slice=slice_id, mode=raw_mode
        ) as wait_span:
            while time.monotonic() < deadline and not self._stop.is_set():
                try:
                    if self.clock() - last_hb >= hb_refresh_s:
                        self.heartbeat_once()
                        last_hb = self.clock()
                    members = self.members(slice_id)
                except ApiException as e:
                    log.warning(
                        "slice %s: membership read failed: %s", slice_id, e
                    )
                    self._stop.wait(self.poll_s)
                    continue
                if not members:
                    break  # slice dissolved (labels removed) mid-round
                # a round the slice has already WON must be honored
                # BEFORE any supersession abort: peers may observe the
                # same commit this poll and flip — aborting now would
                # leave the slice mixed, the exact incoherence this
                # coordinator exists to prevent. Commits are read from
                # the anchor (smallest member), the single fenced
                # location — NOT from whichever node this member
                # currently computes as leader.
                c_mode, c_epoch = _parse_stamp(
                    self._ann(members[0], L.SLICE_COMMIT_ANNOTATION)
                )
                if c_mode == raw_mode and c_epoch > my_done_epoch:
                    commit_epoch = c_epoch
                    break

                # superseded? (VERDICT r2 item 4: an in-flight round must
                # not stall out the full timeout and publish a spurious
                # `failed` when the operator changes the desired mode
                # mid-round). Two signals, either suffices: the agent's
                # mailbox (should_abort), and this node's own desired
                # label re-read from the member list we just fetched.
                if (self.should_abort is not None
                        and self.should_abort(raw_mode)):
                    self._superseded_abort(slice_id, raw_mode)
                me_row = next(
                    (n for n in members
                     if n["metadata"]["name"] == self.node_name), None,
                )
                if me_row is not None:
                    desired_now = (me_row["metadata"].get("labels") or {}
                                   ).get(L.CC_MODE_LABEL)
                    # a REMOVED or empty label maps to the agent's
                    # default mode, which this coordinator doesn't know —
                    # only a present-and-different value is proof of
                    # supersession
                    if desired_now and desired_now != raw_mode:
                        self._superseded_abort(slice_id, raw_mode)
                alive = self._alive(members)
                if not alive:
                    break
                leader = alive[0]["metadata"]["name"]

                if leader == self.node_name:
                    try:
                        self._maybe_commit(raw_mode, alive, members)
                    except ApiException as e:
                        # transient commit-write failure: keep polling (the
                        # ack must stay published, so no retract here)
                        log.warning(
                            "slice %s: commit publish failed: %s",
                            slice_id, e,
                        )
                        self._stop.wait(self.poll_s)
                        continue

                self._stop.wait(self.poll_s)
            wait_span.attrs["committed"] = commit_epoch is not None

        if commit_epoch is not None:
            log.info(
                "slice %s: commit epoch %d observed; flipping locally",
                slice_id, commit_epoch,
            )
            ok = engine.set_mode(raw_mode)
            if ok:
                try:
                    self._annotate_self(
                        DONE_ANNOTATION, f"{raw_mode}:{commit_epoch}"
                    )
                except ApiException as e:
                    log.warning("could not record slice done: %s", e)
            else:
                # local flip failed AFTER the quorum commit: the slice is
                # now visibly half-flipped (cc.mode.state=failed here).
                # Leaving `done` unrecorded keeps the commit actionable,
                # so the agent's repair loop re-converges this laggard
                # without a new quorum round (VERDICT r1 item 8).
                log.error(
                    "slice %s: local flip to %r failed after commit epoch "
                    "%d — slice is half-flipped until repaired",
                    slice_id, raw_mode, commit_epoch,
                )
            return ok

        self._retract_ack()
        shutting_down = self._stop.is_set()
        raise SliceAbortError(
            f"slice {slice_id}: no commit for mode {raw_mode!r} within "
            f"{self.commit_timeout_s:.0f}s"
            + (" (shutting down)" if shutting_down else "")
            + "; refusing to flip — the slice must move atomically",
            shutting_down=shutting_down,
        )

    def _superseded_abort(self, slice_id: str, raw_mode: str) -> None:
        """Abort the round cleanly: retract the ack (the leader must stop
        counting us toward the OLD mode's quorum) and raise with
        superseded set, so the agent skips the failed label and proceeds
        straight to the new mode."""
        self._retract_ack()
        raise SliceAbortError(
            f"slice {slice_id}: round for mode {raw_mode!r} superseded by "
            f"a newer desired mode; aborting without failure",
            superseded=True,
        )

    def _maybe_commit(
        self, raw_mode: str, alive: List[dict], members: List[dict]
    ) -> None:
        """Leader side: publish a fresh commit when every alive member has
        acked this mode and not all of them have already completed it.

        The write is a compare-and-swap on the anchor node (``members[0]``)
        preconditioned on its resourceVersion, so concurrent would-be
        leaders (heartbeat-staleness dual-leader window) produce exactly
        one commit per epoch — the loser's PUT fails with 409 and the next
        poll observes the winner's commit instead."""
        acks = [self._ann(n, L.SLICE_ACK_ANNOTATION) for n in alive]
        if not all(a == raw_mode for a in acks):
            return
        stamps = [_parse_stamp(self._ann(n, DONE_ANNOTATION)) for n in alive]
        laggard_epochs = [e for (m, e) in stamps if m != raw_mode]
        if not laggard_epochs:
            return  # round already completed everywhere; nothing to commit
        # fresh read of the anchor: both the CAS precondition and the
        # re-commit-churn check must see the latest committed state
        anchor_name = members[0]["metadata"]["name"]
        anchor = self.kube.get_node(anchor_name)
        ann = anchor["metadata"].setdefault("annotations", {})
        c_mode, c_epoch = _parse_stamp(ann.get(L.SLICE_COMMIT_ANNOTATION))
        if c_mode == raw_mode and c_epoch > max(laggard_epochs):
            return  # published commit already actionable for every laggard
        # epoch: the highest member rv observed — globally monotone (etcd
        # revision), and necessarily newer than every done epoch from
        # earlier rounds
        epoch = max(
            int(n["metadata"]["resourceVersion"]) for n in alive + [anchor]
        )
        try:
            prev_epoch = int(ann.get(L.SLICE_EPOCH_ANNOTATION, -1))
        except ValueError:
            prev_epoch = -1
        if epoch <= prev_epoch:
            return  # stale view of the slice; re-poll before writing
        ann[L.SLICE_COMMIT_ANNOTATION] = f"{raw_mode}:{epoch}"
        ann[L.SLICE_LEADER_ANNOTATION] = self.node_name
        ann[L.SLICE_EPOCH_ANNOTATION] = str(epoch)
        try:
            self.kube.replace_node(anchor_name, anchor)
        except ConflictError:
            # a concurrent leader won the CAS; their commit (visible on
            # the next poll) fences this epoch — do not retry blindly
            log.info(
                "slice commit CAS lost by %s for %r (epoch %d); deferring "
                "to the concurrent writer",
                self.node_name, raw_mode, epoch,
            )
            return
        log.info(
            "slice leader %s committed %r at epoch %d on anchor %s "
            "(%d acks)",
            self.node_name, raw_mode, epoch, anchor_name, len(acks),
        )
