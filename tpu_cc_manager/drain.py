"""L2 — drain TPU workloads around a mode flip, and publish observed state.

Two drain strategies, selectable per deployment:

1. :class:`ComponentDrainer` — the pause-label protocol, a faithful
   TPU-native rebuild of the reference's gpu-operator eviction module
   (reference gpu_operator_eviction.py): flip each
   ``tpu.google.com/pool.deploy.*`` node label to a paused marker that
   preserves the original value, wait for the component's pods to leave
   the node (2 s poll, 300 s timeout per component, warn-and-continue on
   timeout — reference gpu_operator_eviction.py:174-208), and restore the
   original labels afterwards.

2. :class:`NodeDrainer` — the GKE-native strategy the reference lacks
   (SURVEY.md §7.1): cordon the node (``spec.unschedulable``), evict
   TPU-consuming pods through the Eviction API (respecting PDBs: 429 is
   retried with backoff until the timeout), then uncordon. This is what
   "drain a TPU node pool" actually means without a cooperating operator.

Both preserve the reference's cardinal invariant: **restore is always
attempted, even when the flip failed** (the engine calls ``reschedule()``
in a ``finally`` — reference scripts/cc-manager.sh:210-215).

The observed-state label writer lives here too, mirroring the reference's
placement (gpu_operator_eviction.py:262-286).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

from tpu_cc_manager import labels as L
from tpu_cc_manager.engine import Drainer, FlipTaint, NullDrainer
from tpu_cc_manager.k8s.client import ApiException, KubeClient

log = logging.getLogger("tpu-cc-manager.drain")

#: Per-component pod-deletion wait (reference gpu_operator_eviction.py:136;
#: scripts/cc-manager.sh:275 uses kubectl --timeout=5m).
EVICTION_TIMEOUT_S = 300
#: Poll interval while waiting for pods to go away
#: (reference gpu_operator_eviction.py:200).
EVICTION_POLL_S = 2


def _drain_wait(wake: Optional[threading.Event], poll_s: float) -> None:
    """One drain-wait interval, cut short by the wake event when the
    caller wired one (the agent pulses it from its node-watch delta
    thread, so a restore/taint/cordon change is noticed on the watch
    event instead of the next poll boundary — ISSUE 14's wake
    treatment). ``poll_s`` survives as the liveness fallback: pod
    deletions produce no node event, so the re-check cadence is still
    bounded. Without a wake source this is a plain interruptible
    sleep."""
    if wake is None:
        time.sleep(poll_s)  # ccaudit: allow-poll(no wake source wired: a bare drainer — one-shot CLI without a watch — has nothing to pulse this wait) # ccaudit: allow-stop-aware-wait(same CLI path: there is no stop event either — the agent path always wires the wake, which stop() pulses)
        return
    if wake.wait(poll_s):
        wake.clear()


def set_cc_mode_state_label(kube: KubeClient, node_name: str, value: str) -> None:
    """Publish the observed-state label (reference
    gpu_operator_eviction.py:262-286). Value is the achieved mode or
    'failed' — the Python reference's convention, which we standardize on
    (the bash engine's success/failed variant was a wart, SURVEY.md §5.5)."""
    log.info("setting %s=%s on node %s", L.CC_MODE_STATE_LABEL, value, node_name)
    kube.set_node_labels(node_name, {L.CC_MODE_STATE_LABEL: value})  # ccaudit: allow-direct-node-write(the fail-secure state write: synchronous and ordered by contract, used by one-shot CLIs without a batcher; the agent routes through NodePatchBatcher.write_labels_now)


#: reconcile outcome -> (core/v1 Event reason, Event type); "shutdown"
#: is a termination artifact, not an outcome worth recording
EVENT_FOR_OUTCOME = {
    "success": ("CCModeApplied", "Normal"),
    "failure": ("CCModeFailed", "Warning"),
    "error": ("CCModeFailed", "Warning"),
    "invalid": ("CCModeInvalid", "Warning"),
    "slice_abort": ("CCSliceAborted", "Warning"),
    "fatal": ("CCModeFailed", "Warning"),
}


def build_node_event(node_name: str, reason: str, message: str,
                     etype: str, name: str) -> dict:
    """Core/v1 Event against a Node. Events for cluster-scoped Nodes
    must live in the "default" namespace — a real apiserver rejects
    event.namespace != involvedObject.namespace (which is empty for
    Nodes)."""
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    return {
        "kind": "Event",
        "apiVersion": "v1",
        "metadata": {"name": name, "namespace": "default"},
        "involvedObject": {
            "kind": "Node", "apiVersion": "v1", "name": node_name,
        },
        "reason": reason,
        "message": message,
        "type": etype,
        "source": {"component": "tpu-cc-manager", "host": node_name},
        "firstTimestamp": now,
        "lastTimestamp": now,
        "count": 1,
    }


def build_reconcile_event(
    node_name: str, mode: str, outcome: str, duration_s: float, name: str
) -> Optional[dict]:
    """Core/v1 Event for one reconcile outcome, shared by the agent's
    async recorder and the one-shot CLI (the bash engine builds the same
    shape in _post_event). None for outcomes that don't record."""
    hit = EVENT_FOR_OUTCOME.get(outcome)
    if hit is None:
        return None
    reason, etype = hit
    return build_node_event(
        node_name, reason,
        f"cc mode reconcile to '{mode}': {outcome} in {duration_s:.2f}s",
        etype, name,
    )


def post_event_best_effort(kube: KubeClient, event: dict,
                           warned_before: bool = False) -> Tuple[bool, bool]:
    """Deliver one Event, never raising. Returns (delivered, warned):
    a clientset without Events support (501) is routine and stays at
    debug, anything else (403 RBAC missing, 400 validation) warns — once
    per caller, tracked via ``warned_before`` — because it means the
    deployment is silently losing the feature."""
    try:
        kube.create_event(event["metadata"]["namespace"], event)
        return True, False
    except Exception as e:
        if getattr(e, "status", None) == 501:
            log.debug("event emission skipped: %s", e)
            return False, False
        if not warned_before:
            log.warning(
                "event emission failing (suppressing further warnings): %s",
                e,
            )
            return False, True
        return False, False


class NodeFlipTaint(FlipTaint):
    """Real k8s flip taint: ``tpu.google.com/cc.mode=flipping:NoSchedule``
    held on the node while the engine flips its devices, so the scheduler
    stops placing new TPU pods on a node whose devices are gated. The
    pause labels only speak to a cooperating operator; the taint speaks to
    kube-scheduler itself (SURVEY.md §7.1's GKE-native drain direction).

    ``spec.taints`` is a list, so a merge patch would replace it
    wholesale and wipe taints other controllers (node-lifecycle's
    not-ready/unreachable) add concurrently. Both operations therefore
    use optimistic-concurrency replace: read the node, edit the taint
    list, ``replace_node`` with the read resourceVersion, and retry on
    409 conflict. Both are idempotent."""

    #: bounded retries: losing every race for this long means the node
    #: object is churning so hard the taint is the least of its problems
    MAX_CAS_ATTEMPTS = 8

    def __init__(self, kube: KubeClient, node_name: str,
                 batcher=None, node_hint=None):
        self.kube = kube
        self.node_name = node_name
        #: optional NodePatchBatcher (k8s.batch): every CAS replace this
        #: taint layer performs is a CARRIER for the batcher's pending
        #: label/annotation publications — the node object is already in
        #: hand, so evidence/doctor ride the taint write for free and
        #: the flip's publication round trips collapse into the two
        #: writes the flip makes anyway (ISSUE 6)
        self.batcher = batcher
        #: optional zero-cost seed source (the agent wires the node
        #: watcher's latest_node): the desired-label event that triggers
        #: a reconcile carries a node FRESHER than anything a GET would
        #: return, so the opening taint write can skip its read entirely.
        #: Historically a watcher hint measured slower because async
        #: evidence/event writes landed between the event and the taint
        #: write, dooming the seeded PUT — the batcher removed exactly
        #: those interleaving writes, which is what makes this hint
        #: profitable now.
        self.node_hint = node_hint
        #: node returned by our own last successful replace — the
        #: freshest possible seed for the NEXT write of the same flip
        #: (set -> clear), making the steady-state clear a single round
        #: trip instead of GET+PUT (BENCH phase_p50_s: taint ops are
        #: the flip hot path's dominant cost).
        self._cached: Optional[dict] = None

    def _seed(self, hint_ok: bool = False) -> Optional[dict]:
        if self._cached is not None:
            node, self._cached = self._cached, None
            return node
        if hint_ok and self.node_hint is not None:
            # only the flip's OPENING write (set) may seed from the
            # watcher snapshot: nothing writes the node between the
            # triggering label event and the taint set. The CLOSING
            # write may sit behind drain pause/restore patches the
            # snapshot hasn't caught up with — a stale seed there costs
            # a doomed PUT on top of the fallback read.
            try:
                return self.node_hint()
            except Exception:
                log.debug("taint seed hint failed; falling back to GET",
                          exc_info=True)
        return None

    def invalidate_cache(self) -> None:
        """Drop the cached node. The engine calls this after drain
        pause/restore label patches (which bump the node's rv and would
        make the seeded clear pay a doomed PUT before its fallback)."""
        self._cached = None

    def _cas_loop(self, mutate, cache_result: bool,
                  hint_ok: bool = False) -> bool:
        """Read(or seed)-modify-replace with 409 retry. ``mutate(node)``
        edits in place and returns True to write, None for no-op. A
        no-op judged on a SEED is re-confirmed against a fresh read —
        a stale seed may hide work that is actually needed. Returns
        True only when a replace actually LANDED (a retry that finds
        the work already done returns False).

        ``cache_result``: only the flip's OPENING write (set) caches
        its replace return — it is fresh for the same flip's closing
        write. The closing write must NOT cache: by the next reconcile
        the label change itself has moved the rv, and a stale seed
        costs a doomed PUT before the fallback read (measured: it
        roughly doubled taint_set)."""
        from tpu_cc_manager.k8s.client import ConflictError

        seed = self._seed(hint_ok)
        # ccaudit: allow-retry-discipline(optimistic CAS, not congestion retry: every attempt starts from a FRESH read, contention is at most one other writer per node (the agent), and MAX_CAS_ATTEMPTS caps it — pacing would stretch the flip's critical path for no herd reduction)
        for _ in range(self.MAX_CAS_ATTEMPTS):
            seeded = seed is not None
            node = seed if seeded else self.kube.get_node(self.node_name)
            seed = None
            if mutate(node) is None:
                if seeded:
                    continue  # confirm the no-op on a fresh read
                return False
            # carrier fold: this replace transports whatever the batcher
            # holds (evidence/doctor publications); a conflicted attempt
            # re-folds into the next read, and only a LANDED replace
            # retires the folded generations
            token = (self.batcher.fold_into_node(node)
                     if self.batcher is not None else None)
            try:
                result = self.kube.replace_node(self.node_name, node)  # ccaudit: allow-direct-node-write(this CAS replace IS the batcher's carrier: the fold above transports every pending publication)
                self._cached = result if cache_result else None
                if token and self.batcher is not None:
                    self.batcher.mark_folded(token)
                return True
            except ConflictError:
                continue
        raise ApiException(409, "taint update kept conflicting")

    def _edit_taints(self, edit, cache_result: bool = False,
                     hint_ok: bool = False) -> None:
        def mutate(node):
            taints = list(node.get("spec", {}).get("taints") or [])
            new = edit(taints)
            if new is None:
                return None
            node.setdefault("spec", {})["taints"] = new
            return True

        self._cas_loop(mutate, cache_result, hint_ok)

    def set(self) -> None:
        def add(taints):
            if any(t.get("key") == L.FLIP_TAINT_KEY for t in taints):
                return None
            return taints + [{
                "key": L.FLIP_TAINT_KEY,
                "value": L.FLIP_TAINT_VALUE,
                "effect": L.FLIP_TAINT_EFFECT,
            }]

        log.info("tainting %s %s=%s:%s for the flip", self.node_name,
                 L.FLIP_TAINT_KEY, L.FLIP_TAINT_VALUE, L.FLIP_TAINT_EFFECT)
        self._edit_taints(add, cache_result=True, hint_ok=True)

    def clear(self) -> None:
        def remove(taints):
            kept = [t for t in taints if t.get("key") != L.FLIP_TAINT_KEY]
            return None if len(kept) == len(taints) else kept

        log.info("removing flip taint from %s", self.node_name)
        self._edit_taints(remove)

    def clear_and_publish_state(self, state: str) -> bool:
        """Taint removal + ``cc.mode.state`` label in the SAME CAS
        replace: the node object is already in hand for the taint edit,
        so folding the label in removes one whole PATCH round trip from
        every flip (the reconcile hot path's dominant cost is node-write
        round trips, BENCH phase_p50_s). Atomic as a bonus: observers
        (webhook steering on the state label) can never see the new
        state while the flip taint still repels pods.

        Returns True when the label was published here; False when the
        taint was already absent (no replace happened — the caller's
        plain label write is cheaper than a read-modify-write)."""
        log.info(
            "removing flip taint from %s and setting %s=%s",
            self.node_name, L.CC_MODE_STATE_LABEL, state,
        )
        def mutate(node):
            taints = list(node.get("spec", {}).get("taints") or [])
            kept = [
                t for t in taints if t.get("key") != L.FLIP_TAINT_KEY
            ]
            if len(kept) == len(taints):
                return None  # no taint to clear: plain patch is cheaper
            node.setdefault("spec", {})["taints"] = kept
            node["metadata"].setdefault("labels", {})[
                L.CC_MODE_STATE_LABEL] = state
            return True

        return self._cas_loop(mutate, cache_result=False)


def paused_value(original: str) -> str:
    """Encode the pause marker, preserving the original for restore
    (reference gpu_operator_eviction.py:43-70 '<PAUSED_STR>_<original>')."""
    return f"{L.PAUSED_STR}_{original}"


def unpaused_value(value: str) -> str:
    """Invert paused_value; idempotent on already-unpaused values."""
    prefix = L.PAUSED_STR + "_"
    return value[len(prefix):] if value.startswith(prefix) else value


class ComponentDrainer(Drainer):
    def __init__(
        self,
        kube: KubeClient,
        node_name: str,
        namespace: str = "tpu-system",
        component_labels: Sequence[str] = L.COMPONENT_LABELS,
        timeout_s: float = EVICTION_TIMEOUT_S,
        poll_s: float = EVICTION_POLL_S,
        wake: Optional[threading.Event] = None,
    ):
        self.kube = kube
        self.node_name = node_name
        self.namespace = namespace
        self.component_labels = tuple(component_labels)
        self.timeout_s = timeout_s
        self.poll_s = poll_s
        #: optional wake source for the pod-wait loops (see
        #: engine.Drainer's wake contract): the agent wires its node
        #: watcher's delta pulse here
        self.wake = wake

    # -- reference gpu_operator_eviction.py:98-129 ----------------------
    def fetch_current_component_labels(self) -> Dict[str, str]:
        node = self.kube.get_node(self.node_name)
        node_labels = node["metadata"].get("labels", {})
        return {
            k: node_labels[k] for k in self.component_labels if k in node_labels
        }

    # -- reference gpu_operator_eviction.py:131-215 ---------------------
    def evict(self) -> None:
        current = self.fetch_current_component_labels()
        to_pause = {
            k: paused_value(v)
            for k, v in current.items()
            if not v.startswith(L.PAUSED_STR) and v != "false"
        }
        # node-write tracking for the engine's taint-seed cache: a node
        # with nothing to pause leaves the node object untouched
        self.wrote_node = bool(to_pause)
        if not to_pause:
            log.info("no TPU-stack components deployed on %s; nothing to drain",
                     self.node_name)
            return
        log.info("pausing components on %s: %s", self.node_name,
                 sorted(to_pause))
        self.kube.set_node_labels(self.node_name, to_pause)  # ccaudit: allow-direct-node-write(drain protocol: the pause labels must be visible to the operator BEFORE the pod-wait poll below — deferring them would deadlock the wait)
        for label_key in to_pause:
            self._wait_component_gone(label_key)

    def _wait_component_gone(self, label_key: str) -> None:
        app = L.COMPONENT_APP_LABELS.get(label_key)
        if app is None:
            return
        deadline = time.monotonic() + self.timeout_s
        selector = f"app={app}"
        while True:
            pods = self.kube.list_pods(
                self.namespace,
                label_selector=selector,
                field_selector=f"spec.nodeName={self.node_name}",
            )
            if not pods:
                log.info("component %s drained from %s", app, self.node_name)
                return
            if time.monotonic() >= deadline:
                # warn-and-continue, not fatal
                # (reference gpu_operator_eviction.py:205-207)
                log.warning(
                    "timed out after %ss waiting for %d %s pod(s) to leave %s; "
                    "continuing anyway", self.timeout_s, len(pods), app,
                    self.node_name,
                )
                return
            _drain_wait(self.wake, self.poll_s)

    # -- reference gpu_operator_eviction.py:217-260 ---------------------
    def reschedule(self) -> None:
        """Unpause from live label state (not an in-memory snapshot), so a
        crashed-and-restarted agent can still restore — durable state lives
        in the labels (SURVEY.md §5.4)."""
        restore = {}
        live = self.fetch_current_component_labels()
        for k, v in live.items():
            if v.startswith(L.PAUSED_STR):
                restore[k] = unpaused_value(v)
        if restore:
            log.info("restoring components on %s: %s", self.node_name,
                     sorted(restore))
            self.kube.set_node_labels(self.node_name, restore)  # ccaudit: allow-direct-node-write(drain protocol: restore must land even when the flip failed — it cannot wait behind a batcher flush that may be backing off)
            self.wrote_node = True


class NodeDrainer(Drainer):
    """Cordon + Eviction-API drain of TPU-consuming pods (GKE-native)."""

    def __init__(
        self,
        kube: KubeClient,
        node_name: str,
        namespaces: Sequence[str] = ("default",),
        pod_label_selector: Optional[str] = None,
        timeout_s: float = EVICTION_TIMEOUT_S,
        poll_s: float = EVICTION_POLL_S,
        wake: Optional[threading.Event] = None,
    ):
        self.kube = kube
        self.node_name = node_name
        self.namespaces = tuple(namespaces)
        self.pod_label_selector = pod_label_selector
        self.timeout_s = timeout_s
        self.poll_s = poll_s
        #: optional wake source (see engine.Drainer's wake contract)
        self.wake = wake

    def _cordon(self, value: bool) -> None:
        self.kube.patch_node(self.node_name, {"spec": {"unschedulable": value}})  # ccaudit: allow-direct-node-write(ordered drain step: cordon must precede the evictions issued right after it)

    def _tpu_pods(self):
        out = []
        for ns in self.namespaces:
            for pod in self.kube.list_pods(
                ns,
                label_selector=self.pod_label_selector,
                field_selector=f"spec.nodeName={self.node_name}",
            ):
                out.append((ns, pod["metadata"]["name"]))
        return out

    def evict(self) -> None:
        log.info("cordoning %s and evicting TPU pods", self.node_name)
        self._cordon(True)
        deadline = time.monotonic() + self.timeout_s
        while True:
            pods = self._tpu_pods()
            if not pods:
                return
            blocked = 0
            for ns, name in pods:
                try:
                    self.kube.evict_pod(ns, name)
                except ApiException as e:
                    if e.status == 429:  # PDB says not yet
                        blocked += 1
                    elif e.status != 404:
                        raise
            if blocked == 0 and not self._tpu_pods():
                return
            if time.monotonic() >= deadline:
                log.warning(
                    "timed out draining %s (%d pod(s) still blocked); "
                    "continuing anyway", self.node_name, blocked,
                )
                return
            _drain_wait(self.wake, self.poll_s)

    def reschedule(self) -> None:
        log.info("uncordoning %s", self.node_name)
        self._cordon(False)


def build_drainer(kube: KubeClient, cfg,
                  wake: Optional[threading.Event] = None) -> Drainer:
    """Map an AgentConfig's drain_strategy to a Drainer (single source of
    truth for both the long-lived agent and the one-shot CLI).
    ``wake``: optional watch-delta pulse for the pod-wait loops (the
    agent wires its node watcher's event stream; one-shot CLIs pass
    nothing and keep the plain poll)."""
    if cfg.drain_strategy == "node":
        return NodeDrainer(kube, cfg.node_name, wake=wake)
    if cfg.drain_strategy == "components":
        return ComponentDrainer(
            kube, cfg.node_name, namespace=cfg.operator_namespace,
            wake=wake,
        )
    return NullDrainer()
