"""Mode domain model.

TPU-native mapping of the reference's CC / PPCIe mode semantics
(reference main.py:144-158, scripts/cc-manager.sh:111-123):

- CC modes: ``on`` / ``off`` / ``devtools`` — the TPU
  attestation/confidential-compute state of every chip on the node.
  ``devtools`` is the debuggable-attestation analog of the reference's
  devtools mode.
- ``ici`` — protected-ICI mode, the TPU analog of the reference's PPCIe
  ("protected PCIe") mode (reference main.py:154,456-484): link-level
  protection across the ICI fabric of a slice, covering chips *and* ICI
  switches (the NVSwitch analog, reference main.py:185).

Invariants (reference main.py:512-583):
- CC and ICI are mutually exclusive; enabling one first disables the other.
- ``off`` disables both.
"""

from __future__ import annotations

import enum


class Mode(str, enum.Enum):
    """Desired node security mode (value of the cc.mode label)."""

    ON = "on"
    OFF = "off"
    DEVTOOLS = "devtools"
    ICI = "ici"

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return self.value


#: Modes that are applied through the CC (attestation) state machine.
CC_MODES = (Mode.ON, Mode.OFF, Mode.DEVTOOLS)

#: All valid values for the desired-state label.
VALID_MODES = tuple(m.value for m in Mode)

#: Observed-state label value on any failure (reference
#: gpu_operator_eviction.py:279-286, main.py:300-307).
STATE_FAILED = "failed"


class InvalidModeError(ValueError):
    """Desired mode is not one of VALID_MODES (reference main.py:144-158)."""

    def __init__(self, mode: str):
        super().__init__(
            f"invalid CC mode {mode!r}: must be one of {', '.join(VALID_MODES)}"
        )
        self.mode = mode


def parse_mode(raw: str) -> Mode:
    """Validate and parse a raw label value into a Mode.

    The reference validates in ``CCManager.validate_cc_mode``
    (main.py:144-158) and the bash engine in ``_parse_mode``
    (scripts/cc-manager.sh:125-134); both reject unknown values loudly
    rather than defaulting.
    """
    try:
        return Mode(raw)
    except ValueError:
        raise InvalidModeError(raw) from None
