"""Multi-region federation: N API servers, one fleet (ISSUE 16).

Everything through PR 15 — sharded controllers, the async kube core,
reactive rollout, the incident pipeline — converges one pool behind ONE
API server. Production CC fleets span regions with independent control
planes, asymmetric latency, and separate attestation trust domains.
This module is the federation layer ROADMAP item 2 names:

- **One region-affine ring** (:class:`~tpu_cc_manager.shard.HashRing`
  with ``regions=`` tags): federation members are ``<region>/shard-<k>``
  and every pool's owner is resolved with the home region pinned, so
  controller shards place onto their home region's API server while the
  single hashing scheme keeps placement deterministic across every
  host. :meth:`FederationManager.owner_of` is the ONE sanctioned
  region-aware lookup — ccaudit's ``region-bypass`` rule flags
  partition access that skips it, exactly like shard-bypass.
- **One posture, per-region windows** (:class:`FleetPosture` /
  :func:`posture_from_policy`): a single policy CR expresses the
  desired fleet mode plus ``spec.regionWindows`` — per-region rollout
  offsets. Each region's desired-state write goes through its OWN API
  server inside its own ``desired_write`` trace span (the rollout
  engine's exact patch shape via
  :func:`~tpu_cc_manager.rollout.desired_patch_body`), and the rollout
  judge reads ONLY that region's informer cache: zero cross-region
  steady-state node reads, pinned against FakeKube's
  ``node_read_requests`` counter per region.
- **Region evacuation as a first-class flow** (:meth:`evacuate`):
  the evacuated region's pending posture writes park, its nodes are
  cordoned (``spec.unschedulable``) through its own API server, and
  every OTHER region's still-waiting window collapses to NOW — region
  B absorbs while region A drains, including the evac-races-upgrade
  interleaving simlab's ``federation-*`` scenarios drive.
- **Per-region attestation trust roots** (:class:`RegionTrustDomain`):
  each region's fleet controllers judge quotes under an EXPLICIT key
  posture (never the process-global env), so a revoked root in region
  A drops region A to 'unverifiable' and latches ``attestation_outage``
  there — region B's verified count is untouched (invariant
  ``region_attestation_latch``).

docs/federation.md states the full contract.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.client import ApiException
from tpu_cc_manager.rollout import desired_patch_body
from tpu_cc_manager.shard import DEFAULT_VNODES, HashRing, ShardManager
from tpu_cc_manager.trace import format_traceparent, get_tracer

log = logging.getLogger("tpu-cc-manager.federation")

#: federation ring member id for shard k of a region
MEMBER_FMT = "{region}/shard-{index}"


class FederationError(Exception):
    pass


class RegionTrustDomain:
    """One region's attestation verifier posture: an explicit, mutable
    key tuple — NEVER the process-global env (``tpm_keys``), which
    cannot express two regions trusting different roots in one process.

    ``keys()`` is handed to each region's fleet controllers as their
    ``attest_key`` callable, resolved per scan, so :meth:`rotate` and
    :meth:`revoke` take effect on the next tick without rebuilding
    anything. A revoked domain returns the EMPTY tuple — the explicitly
    keyless posture under which every quote judges 'unverifiable' and
    the region's attestation_outage latch fires; ``None`` (fall back to
    env) is deliberately unreachable from here."""

    def __init__(self, region: str, keys: Sequence[bytes] = ()) -> None:
        self.region = region
        self._lock = threading.Lock()
        self._keys: Tuple[bytes, ...] = tuple(keys)
        self._revoked = False

    def keys(self) -> Tuple[bytes, ...]:
        with self._lock:
            return () if self._revoked else self._keys

    def rotate(self, new_key: bytes) -> None:
        """New primary, old keys kept as the rotation tail (attest.py's
        still-old-quotes-must-verify rule)."""
        with self._lock:
            self._keys = (new_key,) + self._keys

    def revoke(self) -> None:
        """Drop THIS region's trust wholesale (compromised root). Other
        regions' domains are separate objects — nothing spills."""
        with self._lock:
            self._revoked = True

    def restore(self) -> None:
        with self._lock:
            self._revoked = False

    @property
    def revoked(self) -> bool:
        with self._lock:
            return self._revoked


@dataclasses.dataclass
class RegionSpec:
    """One region's wiring: its API server (client factory), its pool
    partition of the fleet, and its attestation trust domain (None =
    the process-global env posture — single-region compatibility)."""

    name: str
    client_factory: Callable[[], Any]
    pools: Sequence[str]
    trust_domain: Optional[RegionTrustDomain] = None


@dataclasses.dataclass
class FleetPosture:
    """ONE desired fleet posture: the mode every region converges to,
    with per-region window offsets (seconds from :meth:`apply_posture`;
    absent region = opens immediately). ``source`` names the policy CR
    it came from, for the artifact."""

    mode: str
    windows: Dict[str, float] = dataclasses.field(default_factory=dict)
    source: Optional[str] = None


def posture_from_policy(policy: dict) -> FleetPosture:
    """A cross-region policy CR -> FleetPosture: ``spec.mode`` plus
    ``spec.regionWindows`` (policy.parse_policy_spec validates both;
    PolicySpecError propagates — one bad CR must surface, not
    half-apply)."""
    from tpu_cc_manager.policy import parse_policy_spec

    spec = parse_policy_spec(policy)
    return FleetPosture(
        mode=spec["mode"],
        windows=dict(spec["region_windows"]),
        source=(policy.get("metadata") or {}).get("name"),
    )


class RegionRingView:
    """A region-scoped facade over the ONE federation ring: every
    lookup resolves with the home region pinned, so a region's
    ShardManager partitions its pools exactly where
    :meth:`FederationManager.owner_of` says they live — one hashing
    scheme, no second source of placement truth."""

    def __init__(self, ring: HashRing, region: str) -> None:
        self.ring = ring
        self.region = region
        self.members = tuple(ring.members_in(region))
        if not self.members:
            raise FederationError(
                f"region {region!r} has no ring members"
            )
        self.vnodes = ring.vnodes

    def owner_of(self, key: str, region: Optional[str] = None) -> str:
        return self.ring.owner_of(key, region=self.region)

    def partition(
        self, keys: Sequence[str],
        region_of: Optional[Callable[[str], Optional[str]]] = None,
    ) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {m: [] for m in self.members}
        for key in keys:
            out[self.owner_of(key)].append(key)
        for v in out.values():
            v.sort()
        return out


class FederationManager:
    """N regions, each its own API server + per-region ShardManager
    (own informer, own trust domain), one federation-wide region-affine
    ring, one posture."""

    def __init__(
        self,
        regions: Sequence[RegionSpec],
        *,
        pool_label: str,
        shards_per_region: int = 1,
        hosts_per_region: Optional[int] = None,
        selector: str = L.TPU_ACCELERATOR_LABEL,
        policy: bool = False,
        fleet_interval_s: float = 1.0,
        lease_duration_s: float = 2.0,
        renew_period_s: float = 0.5,
        retry_period_s: float = 0.25,
        vnodes: int = DEFAULT_VNODES,
    ) -> None:
        if not regions:
            raise FederationError("a federation needs at least one region")
        names = [r.name for r in regions]
        if len(set(names)) != len(names):
            raise FederationError(f"duplicate region names: {sorted(names)}")
        if shards_per_region < 1:
            raise FederationError(
                f"shards_per_region must be >= 1, got {shards_per_region}"
            )
        self.specs: Dict[str, RegionSpec] = {r.name: r for r in regions}
        self.pool_label = pool_label
        self.selector = selector
        #: pool -> home region. The table is spec-derived; read it ONLY
        #: through region_of_pool / owner_of — ccaudit's region-bypass
        #: rule flags anything else, mirroring shard.py's partition rule
        self._pool_region: Dict[str, str] = {}
        for r in regions:
            for pool in r.pools:
                if pool in self._pool_region:
                    raise FederationError(
                        f"pool {pool!r} claimed by both "
                        f"{self._pool_region[pool]!r} and {r.name!r}"  # ccaudit: allow-region-bypass(constructor builds the table from the spec; duplicate-claim error names the prior owner)
                    )
                self._pool_region[pool] = r.name  # ccaudit: allow-region-bypass(constructor builds the table from the spec — the one sanctioned write site)
        members: List[str] = []
        tags: Dict[str, str] = {}
        for r in regions:
            for k in range(shards_per_region):
                m = MEMBER_FMT.format(region=r.name, index=k)
                members.append(m)
                tags[m] = r.name
        #: THE federation ring: every region's manager sees it through
        #: a RegionRingView, so placement is one deterministic scheme
        self.ring = HashRing(members, vnodes=vnodes, regions=tags)
        self.managers: Dict[str, ShardManager] = {}
        for r in regions:
            domain = r.trust_domain
            self.managers[r.name] = ShardManager(
                r.client_factory,
                shard_ids=self.ring.members_in(r.name),
                ring=RegionRingView(self.ring, r.name),
                pools=list(r.pools),
                pool_label=pool_label,
                hosts=hosts_per_region,
                selector=selector,
                policy=policy,
                fleet_interval_s=fleet_interval_s,
                lease_duration_s=lease_duration_s,
                renew_period_s=renew_period_s,
                retry_period_s=retry_period_s,
                port=0,
                attest_key=(domain.keys if domain is not None else None),
                region=r.name,
            )
        #: per-region write clients (posture patches, cordons): every
        #: region's writes go through ITS API server, never a sibling's
        self._clients: Dict[str, Any] = {
            r.name: r.client_factory() for r in regions
        }
        self._lock = threading.Lock()
        self._posture: Optional[FleetPosture] = None
        self._generation = 0
        self._evacuated: Set[str] = set()
        self._partitioned: Set[str] = set()
        self._evacuations: List[dict] = []
        #: set by evacuate(): every still-waiting region window
        #: collapses to NOW (absorb). Re-created per posture.
        self._absorb = threading.Event()
        self._stop = threading.Event()
        self._workers: List[threading.Thread] = []
        self._started = False

    # ------------------------------------------------------------ placement
    def region_of_pool(self, pool: str) -> str:
        """A pool's home region — the spec-derived half of the one
        sanctioned lookup."""
        region = self._pool_region.get(pool)
        if region is None:
            raise FederationError(f"pool {pool!r} belongs to no region")
        return region

    def owner_of(self, pool: str) -> Tuple[str, str]:
        """THE region-aware owner lookup: (home region, owning ring
        member). Controller shards place onto their home region's API
        server because the ring walk is pinned to that region; the
        global fallback fires only when the whole region is absent."""
        region = self.region_of_pool(pool)
        return region, self.ring.owner_of(pool, region=region)

    def pools_in_region(self, region: str) -> List[str]:
        if region not in self.specs:
            raise FederationError(f"unknown region {region!r}")
        return sorted(list(self.specs[region].pools))

    @property
    def regions(self) -> List[str]:
        return sorted(self.specs)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FederationManager":
        for name in sorted(self.managers):
            self.managers[name].start()
        self._started = True
        return self

    def stop(self) -> None:
        self._stop.set()
        self._absorb.set()  # wake any window still waiting
        for t in self._workers:
            t.join(timeout=5)
        for m in self.managers.values():
            m.stop()

    def wait_covered(self, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        for m in self.managers.values():
            remaining = max(0.0, deadline - time.monotonic())
            if not m.wait_covered(timeout_s=remaining):
                return False
        return True

    # -------------------------------------------------------------- posture
    def apply_posture(self, posture: FleetPosture) -> None:
        """Launch ONE fleet posture: a window worker per region waits
        its offset (or until an evacuation elsewhere collapses it to
        now), then writes the desired label + trace annotation to every
        node of that region's pools THROUGH that region's API server.
        A partitioned region's write defers, retrying until the
        partition heals; an evacuated region's write parks forever."""
        with self._lock:
            self._posture = posture
            self._generation += 1
            gen = self._generation
            self._absorb = threading.Event()
            absorb = self._absorb
        log.info("posture %r (source=%s) windows=%s",
                 posture.mode, posture.source, posture.windows)
        for region in self.regions:
            t = threading.Thread(
                target=self._region_window_worker,
                args=(region, posture, gen, absorb),
                daemon=True,
                name=f"fed-window-{region}",
            )
            t.start()
            self._workers.append(t)

    def _region_window_worker(
        self, region: str, posture: FleetPosture, gen: int,
        absorb: threading.Event,
    ) -> None:
        offset = float(posture.windows.get(region, 0.0))
        if offset > 0:
            # the absorb event is the ONLY early exit: an evacuation
            # elsewhere means this region opens NOW to take the load
            absorb.wait(timeout=offset)
        while not self._stop.is_set():
            with self._lock:
                if gen != self._generation:
                    return  # superseded by a newer posture
                if region in self._evacuated:
                    log.info("region %s: posture %r parked (evacuated)",
                             region, posture.mode)
                    return
            try:
                self._write_region_desired(region, posture.mode)
                return
            except ApiException as e:
                # partition / blackout: desired state DEFERS — the
                # write lands when the region heals, never half-lands
                log.warning("region %s: posture write deferred: %s",
                            region, e)
                if self._stop.wait(0.2):
                    return

    def _write_region_desired(self, region: str, mode: str) -> None:
        names = self._region_node_names(region)
        # ONE desired_write span per region per posture: its
        # traceparent rides the cc.trace annotation in the SAME patch
        # as the desired label (rollout._launch's contract), so the
        # cross-region e2e convergence axis stitches every region's
        # desired-write -> state-publish story from trace ids alone
        with get_tracer().span(
            "desired_write", group=f"region-{region}", mode=mode,
            nodes=len(names),
        ) as span:
            context = format_traceparent(span)
            client = self._clients[region]
            for name in names:
                client.patch_node(name, desired_patch_body(mode, context))
        log.info("region %s: desired %r written to %d nodes",
                 region, mode, len(names))

    def _region_node_names(self, region: str) -> List[str]:
        """The region's pool nodes, read from the region's OWN informer
        cache (a warm informer list is zero API round trips — and by
        construction never a cross-region read)."""
        manager = self.managers[region]
        pools = frozenset(self.specs[region].pools)
        pool_label = self.pool_label
        cached = manager.informer.client(
            self._clients[region],
            node_filter=lambda n: ((n.get("metadata") or {})
                                   .get("labels") or {})
            .get(pool_label) in pools,
        )
        nodes = cached.list_nodes(self.selector)
        return sorted(
            (n.get("metadata") or {}).get("name", "") for n in nodes
        )

    # ------------------------------------------------------------- judging
    def region_converged(self, region: str, mode: str) -> bool:
        """The per-region rollout judge: every pool node's state label
        equals ``mode``, read from THAT region's informer cache only —
        the zero-cross-region-reads contract the federation tests pin
        against each FakeKube's node_read_requests counter."""
        manager = self.managers[region]
        pools = frozenset(self.specs[region].pools)
        nodes = manager.informer.client(self._clients[region]).list_nodes(
            self.selector
        )
        saw = 0
        for n in nodes:
            labels = (n.get("metadata") or {}).get("labels") or {}
            if labels.get(self.pool_label) not in pools:
                continue
            saw += 1
            if labels.get(L.CC_MODE_STATE_LABEL) != mode:
                return False
        return saw > 0

    def region_cordoned(self, region: str) -> bool:
        """Evacuation's success check: every pool node in the region is
        unschedulable (again purely from the region's informer cache)."""
        manager = self.managers[region]
        pools = frozenset(self.specs[region].pools)
        nodes = manager.informer.client(self._clients[region]).list_nodes(
            self.selector
        )
        saw = 0
        for n in nodes:
            labels = (n.get("metadata") or {}).get("labels") or {}
            if labels.get(self.pool_label) not in pools:
                continue
            saw += 1
            if not (n.get("spec") or {}).get("unschedulable"):
                return False
        return saw > 0

    def wait_posture(self, timeout_s: float = 60.0) -> bool:
        """Block until the active posture holds fleet-wide: every
        non-evacuated region converged to its mode, every evacuated
        region fully cordoned."""
        with self._lock:
            posture = self._posture
        if posture is None:
            raise FederationError("no posture applied")
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._posture_holds(posture.mode):
                return True
            if self._stop.wait(0.05):
                return False
        return self._posture_holds(posture.mode)

    def _posture_holds(self, mode: str) -> bool:
        with self._lock:
            evacuated = set(self._evacuated)
        for region in self.regions:
            if region in evacuated:
                if not self.region_cordoned(region):
                    return False
            elif not self.region_converged(region, mode):
                return False
        return True

    # ---------------------------------------------------------- evacuation
    def evacuate(self, region: str) -> dict:
        """Drain one region while the others absorb: park the region's
        posture writes, collapse every OTHER region's still-waiting
        window to NOW, and cordon the region's nodes through its own
        API server (retrying through faults — evacuation is exactly
        the flow that races partitions and upgrades). Returns the
        fault-log entry the simlab artifact carries."""
        if region not in self.specs:
            raise FederationError(f"unknown region {region!r}")
        t0 = time.monotonic()
        with self._lock:
            already = region in self._evacuated
            self._evacuated.add(region)
            absorb = self._absorb
            entry = {
                "region": region,
                "already_evacuated": already,
                "cordoned": 0,
                "cordon_s": None,
            }
            self._evacuations.append(entry)
        absorb.set()
        t = threading.Thread(
            target=self._cordon_region, args=(region, entry, t0),
            daemon=True, name=f"fed-evac-{region}",
        )
        t.start()
        self._workers.append(t)
        log.warning("region %s: evacuation started (others absorb)",
                    region)
        return dict(entry)

    def _cordon_region(self, region: str, entry: dict, t0: float) -> None:
        from tpu_cc_manager.watch import jittered_backoff

        client = self._clients[region]
        pending = self._region_node_names(region)
        done = 0
        rounds = 0
        while pending and not self._stop.is_set():
            still: List[str] = []
            for name in pending:
                try:
                    client.patch_node(
                        name, {"spec": {"unschedulable": True}}
                    )
                    done += 1
                except ApiException:
                    still.append(name)
            pending = still
            # nodes that failed this round retry on a growing jittered
            # pause: a partitioned region's API server comes back to a
            # paced trickle, not a per-200ms full-region patch storm
            rounds += 1
            if pending and self._stop.wait(
                jittered_backoff(0.2, rounds, cap_s=5.0)
            ):
                break
        with self._lock:
            entry["cordoned"] = done
            entry["cordon_s"] = round(time.monotonic() - t0, 4)
        log.info("region %s: %d nodes cordoned in %.2fs",
                 region, done, entry["cordon_s"])

    # ----------------------------------------------------------- partitions
    def set_partitioned(self, region: str, partitioned: bool) -> None:
        """Bookkeeping hook for the fault injector (the real deferral
        is the ApiException retry loop in the window worker — this just
        makes the artifact's stats truthful about WHY a write waited)."""
        with self._lock:
            if partitioned:
                self._partitioned.add(region)
            else:
                self._partitioned.discard(region)

    # -------------------------------------------------------------- reading
    def attestation_summary(self) -> Dict[str, dict]:
        """Per-region attestation posture for the artifact: revocation
        state plus each region's latest fleet-scan attestation audit
        (merged over the region's shard bundles)."""
        out: Dict[str, dict] = {}
        for region in self.regions:
            domain = self.specs[region].trust_domain
            verified = 0
            outage: List[str] = []
            seen = False
            for bundle in self.managers[region].bundles():
                report = bundle.fleet.last_report or {}
                audit = report.get("evidence_audit") or {}
                if audit.get("attestation_seen"):
                    seen = True
                verified += audit.get("attestation_verified", 0)
                outage.extend(audit.get("attestation_outage", []))
            out[region] = {
                "revoked": (domain.revoked if domain is not None
                            else False),
                "attestation_seen": seen,
                "attestation_verified": verified,
                "attestation_outage": sorted(set(outage)),
            }
        return out

    def stats(self) -> dict:
        with self._lock:
            posture = self._posture
            evacuated = sorted(self._evacuated)
            partitioned = sorted(self._partitioned)
            evacuations = [dict(e) for e in self._evacuations]
        return {
            "regions": self.regions,
            "ring_members": list(self.ring.members),
            "posture": (
                None if posture is None else {
                    "mode": posture.mode,
                    "windows": dict(posture.windows),
                    "source": posture.source,
                }
            ),
            "evacuated": evacuated,
            "partitioned": partitioned,
            "evacuations": evacuations,
            "managers": {
                region: self.managers[region].stats()
                for region in self.regions
            },
        }
