"""Flight recorder — the bounded per-process black box (ISSUE 8).

Metrics answer "what is the rate of X"; traces answer "where did this
reconcile's time go". What neither answers after a crash or a stuck
flip is "what were the last things this process DID, and what was the
host doing while it did them" — the reference has nothing (SURVEY.md
§5.5), and until now neither did we: a reconcile failure left a log
line and a ``failed`` label, and the r05 real-chip flip regression sat
unattributed partly because nobody recorded host contention around the
flip window (ROADMAP item 1's missing sensor).

:class:`FlightRecorder` keeps three bounded rings — recent completed
spans (wired as a tracer sink), structured events (:meth:`note`), and
host-contention samples (:meth:`sample` / :meth:`bracket`, /proc-based,
bracketing every device flip) — plus a metrics-snapshot hook. A *dump*
serializes all of it with a reason stamp into one JSON artifact:

- on **reconcile failure** (throttled — one dump per
  ``min_dump_interval_s``, a flapping device must not fill the disk);
- on **SIGTERM** (:func:`install_sigterm_dump`, chaining the previous
  handler), so the kubelet killing a wedged agent leaves the black box
  behind;
- on demand via ``GET /debug/flightrec`` on the health server (no file
  written — the snapshot IS the response body).

simlab gives every replica its own recorder and stitches the
recordings fleet-wide by trace id into the artifact's fleet timeline
(simlab/runner.py). Dump schema: docs/observability.md.

Everything here is observability: no method raises into a reconcile,
and an unreadable /proc degrades to an ``unavailable`` sample.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Optional

log = logging.getLogger("tpu-cc-manager.flightrec")

#: dump schema version (tests pin the shape; bump on breaking change)
SCHEMA_VERSION = 1


def sample_host() -> Dict[str, Any]:
    """One cheap host-contention sample from /proc: load averages,
    total-CPU jiffies (delta between two samples = host-wide CPU
    pressure), this process's own utime/stime, and available memory.
    ~3 file reads, no allocation beyond the dict — cheap enough to
    bracket every flip. Returns ``{"unavailable": True}`` where /proc
    is missing (non-Linux dev box)."""
    out: Dict[str, Any] = {"at": round(time.time(), 3)}
    try:
        with open("/proc/loadavg") as f:
            parts = f.read().split()
        out["load1"], out["load5"] = float(parts[0]), float(parts[1])
        out["runnable"] = parts[3]  # "running/total" threads
        with open("/proc/stat") as f:
            cpu = f.readline().split()
        # user+nice+system+idle+iowait+irq+softirq+steal
        out["cpu_total_jiffies"] = sum(int(x) for x in cpu[1:9])
        out["cpu_idle_jiffies"] = int(cpu[4])
        with open("/proc/self/stat") as f:
            me = f.read().rsplit(")", 1)[1].split()
        # fields 14/15 (1-based, after comm): utime, stime
        out["self_utime_jiffies"] = int(me[11])
        out["self_stime_jiffies"] = int(me[12])
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    out["mem_available_kb"] = int(line.split()[1])
                    break
    except Exception:  # ccaudit: allow-swallow(observability sensor: an unreadable /proc degrades to an explicit "unavailable" sample — the degradation IS the signal, and a sampler that raises would take down the flip it brackets)
        return {"at": out["at"], "unavailable": True}
    return out


class FlightRecorder:
    """Bounded black box for one process (or one simlab replica)."""

    #: ring sizes: recent-history breadth, not archival — the JSONL
    #: trace sink is the archival surface
    SPAN_RING = 512
    EVENT_RING = 256
    SAMPLE_RING = 128

    def __init__(
        self,
        name: str = "",
        *,
        metrics: Optional[Any] = None,
        dump_dir: Optional[str] = None,
        min_dump_interval_s: float = 30.0,
        span_ring: int = SPAN_RING,
        event_ring: int = EVENT_RING,
        sample_ring: int = SAMPLE_RING,
        tsring: Optional[Any] = None,
        profiler: Optional[Any] = None,
    ):
        #: identity stamped into every dump (node name for agents,
        #: replica name in simlab)
        self.name = name
        #: object with ``.render() -> str`` (obs.Metrics and both
        #: controller metric sets) or a callable returning a dict;
        #: snapshotted at dump time, never continuously
        self._metrics = metrics
        #: optional tsring.TimeSeriesRing (ISSUE 9): dumps then carry
        #: the windowed rate/quantile history LEADING UP TO the crash,
        #: not just the instant of it (points elided — dumps stay small)
        self.tsring = tsring
        #: optional profiler.SamplingProfiler (ISSUE 15): when it holds
        #: samples at dump time (armed by an operator or the watchdog),
        #: the dump embeds the folded-stack summary — the black box
        #: then says what the interpreter was EXECUTING, not only what
        #: the process did
        self.profiler = profiler
        self.dump_dir = dump_dir or os.environ.get(
            "TPU_CC_FLIGHTREC_DIR") or None
        self.min_dump_interval_s = min_dump_interval_s
        self._spans: deque = deque(maxlen=span_ring)
        self._events: deque = deque(maxlen=event_ring)
        self._samples: deque = deque(maxlen=sample_ring)
        self._lock = threading.Lock()
        self._last_dump = 0.0  # monotonic; throttles maybe_dump
        self.dumps_total = 0
        self._dump_seq = 0

    # ------------------------------------------------------------ feeding
    def observe_span(self, span: Any) -> None:
        """Tracer sink: retain the completed span (as its dict)."""
        try:
            d = span.to_dict() if hasattr(span, "to_dict") else dict(span)
        except Exception:  # ccaudit: allow-swallow(tracer-sink contract: a sink must never raise into the reconcile that produced the span; an unserializable span is dropped from the ring, the JSONL sink still has it)
            return
        with self._lock:
            self._spans.append(d)

    def note(self, kind: str, **fields: Any) -> None:
        """Record one structured event (reconcile outcome, repair fired,
        watch error burst, ...). Never raises."""
        entry = {"at": round(time.time(), 3), "kind": kind}
        entry.update(fields)
        with self._lock:
            self._events.append(entry)

    def sample(self, tag: str,
               trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Take one host-contention sample, tagged. ``trace_id``
        (ISSUE 15) joins the sample to the trace it brackets, so an
        incident reader correlates "host was loaded" with "THIS flip
        was slow" instead of eyeballing timestamps."""
        s = sample_host()
        s["tag"] = tag
        if trace_id:
            s["trace"] = trace_id
        with self._lock:
            self._samples.append(s)
        return s

    @contextmanager
    def bracket(self, tag: str,
                trace_id: Optional[str] = None) -> Iterator[None]:
        """Host samples BRACKETING a critical section — the engine
        wraps every device flip, so a slow real-chip flip carries the
        host-contention evidence ROADMAP item 1 needs (was the 4.43 s
        flip the chip, or a noisy neighbor?). The engine passes the
        flip's trace id so both samples carry the stitch key."""
        self.sample(f"{tag}:pre", trace_id=trace_id)
        try:
            yield
        finally:
            self.sample(f"{tag}:post", trace_id=trace_id)

    # ------------------------------------------------------------ reading
    def _metrics_snapshot(self) -> Any:
        m = self._metrics
        if m is None:
            return None
        try:
            if hasattr(m, "render"):
                return {"exposition": m.render()}
            if callable(m):
                return m()
        except Exception:
            log.warning("flightrec metrics snapshot failed", exc_info=True)
        return None

    def snapshot(self, reason: str = "inspect") -> Dict[str, Any]:
        """The full black-box contents as one JSON-able document (the
        dump body, and the ``/debug/flightrec`` response)."""
        with self._lock:
            spans = list(self._spans)
            events = list(self._events)
            samples = list(self._samples)
        doc = {
            "flightrec_version": SCHEMA_VERSION,
            "reason": reason,
            "at": round(time.time(), 3),
            "name": self.name,
            "spans": spans,
            "events": events,
            "host_samples": samples,
            "metrics": self._metrics_snapshot(),
        }
        if self.tsring is not None:
            try:
                doc["timeseries"] = self.tsring.to_doc(
                    include_points=False)
            except Exception:  # ccaudit: allow-swallow(black-box contract: a broken time-series ring must cost the dump one section, never the dump itself — the warning names the loss)
                log.warning("flightrec timeseries embed failed",
                            exc_info=True)
        if self.profiler is not None:
            try:
                if getattr(self.profiler, "samples_total", 0):
                    # only when something was actually sampled: an
                    # idle (never-armed) profiler must not bloat every
                    # dump with an empty section
                    doc["profile"] = self.profiler.summary()
            except Exception:  # ccaudit: allow-swallow(black-box contract: a broken profiler must cost the dump one section, never the dump itself — the warning names the loss)
                log.warning("flightrec profile embed failed",
                            exc_info=True)
        return doc

    # ------------------------------------------------------------ dumping
    def dump(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Write one dump artifact now; returns its path, or None when
        no dump directory is configured or the write failed (logged —
        a black box must never take down what it records)."""
        if path is None:
            if not self.dump_dir:
                return None
            with self._lock:
                self._dump_seq += 1
                seq = self._dump_seq
            fname = (
                f"flightrec-{self.name or 'proc'}-{os.getpid()}-"
                f"{seq:04d}-{reason}.json"
            )
            path = os.path.join(self.dump_dir, fname)
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            doc = self.snapshot(reason)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)  # a dump is whole or absent, never torn
        except Exception:
            log.warning("flight-recorder dump failed", exc_info=True)
            return None
        with self._lock:
            self.dumps_total += 1
            self._last_dump = time.monotonic()
        log.info("flight recorder dumped (%s): %s", reason, path)
        return path

    def maybe_dump(self, reason: str) -> Optional[str]:
        """Throttled dump for recurring triggers (reconcile failures):
        at most one per ``min_dump_interval_s`` — a flapping device
        must not fill the disk with near-identical dumps."""
        with self._lock:
            if (self._last_dump
                    and time.monotonic() - self._last_dump
                    < self.min_dump_interval_s):
                return None
        return self.dump(reason)


# ----------------------------------------------------- process plumbing

_default = FlightRecorder()


def get_recorder() -> FlightRecorder:
    """The process-wide recorder — what code without an injected
    recorder (one-shot CLIs, the engine's default path) records into."""
    return _default


def set_recorder(recorder: Optional[FlightRecorder]) -> None:
    """Swap the process-wide recorder (tests use this for isolation)."""
    global _default
    _default = recorder or FlightRecorder()


def install_sigterm_dump(
    recorder: FlightRecorder,
    signum: int = signal.SIGTERM,
) -> Optional[Callable[[int, Any], None]]:
    """Make SIGTERM (the kubelet's pod-stop signal) leave the black box
    behind before the process dies: dump, then CHAIN to whatever
    handler was installed before (the agent's clean-shutdown handler,
    or the default action re-raised so the exit code stays honest).
    Returns the installed handler (tests invoke it directly), or None
    when not on the main thread (embedded use — Python only allows
    signal handler installation there).

    The dump runs on a WORKER thread with a bounded join, never inline
    in the handler: signal handlers run on the main thread between
    bytecodes, and the main thread may hold the recorder's (or the
    logging module's) non-reentrant lock at delivery time — an inline
    dump would deadlock the very shutdown it instruments. If the dump
    can't finish inside the bound (a held lock, a hung disk), the
    chain proceeds without it: a missing black box must never turn a
    clean kubelet stop into a SIGKILL."""
    previous = signal.getsignal(signum)

    def handler(sig: int, frame: Any) -> None:
        t = threading.Thread(
            target=lambda: recorder.dump("sigterm"),
            daemon=True, name="flightrec-sigterm-dump",
        )
        t.start()
        t.join(timeout=5.0)
        if callable(previous):
            previous(sig, frame)
        elif previous == signal.SIG_DFL:
            # restore + re-raise: the process must still die of
            # SIGTERM (exit status and the kubelet's view stay honest)
            signal.signal(sig, signal.SIG_DFL)
            signal.raise_signal(sig)

    try:
        signal.signal(signum, handler)
    except ValueError:
        return None  # not the main thread
    return handler


def stitch_by_trace(
    recordings: List[Dict[str, Any]],
) -> Dict[str, List[Dict[str, Any]]]:
    """Group spans from many recordings (each a :meth:`snapshot` dict)
    by trace id — the fleet-timeline primitive: a controller's
    desired-write span and every replica reconcile that adopted its
    context land in one bucket, whatever process recorded them."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for rec in recordings:
        for span in rec.get("spans") or []:
            tid = span.get("trace")
            if not tid:
                continue
            entry = dict(span)
            if rec.get("name"):
                entry.setdefault("recorder", rec["name"])
            by_trace.setdefault(tid, []).append(entry)
    for spans in by_trace.values():
        spans.sort(key=lambda s: s.get("start_ts") or 0.0)
    return by_trace
