"""In-memory fake clientset with a faithful watch implementation.

The reference has no test fixtures at all (SURVEY.md §4); this fake is the
foundation of the test pyramid the TPU build adds. It reproduces the API
server behaviors the agents' robustness code exists for:

- monotonically increasing resourceVersion on every mutation;
- watch streams that replay history from a given rv, then block for new
  events until a server-side timeout;
- bounded watch history with 410 Gone when a watcher resumes from a
  compacted rv (reference main.py:675-687 handles this);
- optimistic-concurrency replace (409) for leader-election CAS;
- PDB-blocked eviction (429);
- injectable watch errors to exercise the consecutive-error fatal path
  (reference main.py:664-673).

Thread-safe: N agent threads + test thread may mutate concurrently (the
multi-node simulation in tests/test_multinode.py runs 32 agents against
one instance).
"""

from __future__ import annotations

import bisect
import copy
import json
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from tpu_cc_manager.k8s.client import ApiException, ConflictError, KubeClient
from tpu_cc_manager.k8s.objects import match_selector, merge_patch


class _WatchEvent:
    """One retained watch event. The snapshot is deep-copied ONCE at
    record time and never mutated; ``wire()`` caches the serialized
    NDJSON line so N watchers fanning one event out over HTTP pay ONE
    json.dumps instead of N (ISSUE 11: the apiserver's fan-out cost
    used to scale O(history x watchers) in encoding alone)."""

    __slots__ = ("rv", "etype", "obj", "_wire")

    def __init__(self, rv: int, etype: str, obj: dict):
        self.rv = rv
        self.etype = etype
        self.obj = obj
        self._wire: Optional[bytes] = None

    def wire(self) -> bytes:
        # benign last-writer-wins: two watchers racing this encode the
        # same immutable snapshot to identical bytes
        # ccaudit: allow-race-lockset(idempotent memoization of an immutable snapshot: concurrent writers produce byte-identical values, a lost update costs one redundant json.dumps)
        if self._wire is None:
            self._wire = json.dumps(
                {"type": self.etype, "object": self.obj}
            ).encode() + b"\n"
        return self._wire


def _paginate(
    items: List[dict], limit: Optional[int], cont: Optional[str]
) -> Tuple[List[dict], Optional[str]]:
    """Name-ordered chunking with an offset continue token (the real API
    server's chunked-LIST contract, close enough for client testing)."""
    items.sort(key=lambda o: o["metadata"]["name"])
    try:
        start = int(cont) if cont else 0
    except ValueError:
        raise ApiException(410, f"invalid continue token {cont!r}") from None
    if limit is None or limit <= 0:
        return items[start:], None
    page = items[start:start + limit]
    nxt = start + limit
    return page, (str(nxt) if nxt < len(items) else None)


class FakeKube(KubeClient):
    def __init__(self, watch_history_limit: int = 1000):
        self._lock = threading.Condition()
        self._nodes: Dict[str, dict] = {}
        self._pods: Dict[Tuple[str, str], dict] = {}
        self._rv = 0
        # watch history: _WatchEvent records plus a parallel rv list so
        # watchers bisect to their resume point. Compaction is CHUNKED
        # (trim only past limit + chunk, back down to limit): the old
        # trim-on-every-write sliced a full limit-sized list per write
        # once the ring filled — O(limit) per write, the quiet half of
        # the fan-out wall long simlab runs hit (ISSUE 11 satellite).
        # The 410 contract is unchanged: a resume below the oldest
        # retained rv still fails at establishment.
        self._events: List[_WatchEvent] = []
        self._event_rvs: List[int] = []
        self._history_limit = watch_history_limit
        # fault injection
        self.pdb_blocked: set = set()  # {(ns, name)} -> evict raises 429
        self.fail_next_watches = 0  # next N watch_nodes calls raise 500
        #: next N node LISTs answer 429 (API-server overload storm, the
        #: priority-and-fairness rejection clients must retry through)
        self.fail_next_lists = 0
        #: next N node WRITES (patch/replace) answer 429 — the write-path
        #: overload storm the coalescing publish core must absorb
        #: without losing its newest generation (ISSUE 6)
        self.fail_next_node_writes = 0
        self.patch_delay_s = 0.0  # simulated API latency
        #: regional API blackout (federation, ISSUE 16): while set,
        #: every API verb answers 503 and in-flight watches sever —
        #: the whole control plane of ONE region going dark. Driver
        #: out-of-band surfaces (peek_node_label, add_node,
        #: set_node_labels_direct) stay up: measurement and scenario
        #: input must survive the fault they script.
        self.blackout = False
        #: inter-region latency skew (federation): a flat per-request
        #: delay on every API verb, slept OUTSIDE the store lock so a
        #: slow region slows its callers, never its own event fan-out
        self.response_delay_s = 0.0
        # Write accounting (ISSUE 6 satellite): batching merges several
        # LOGICAL mutations into one HTTP round trip, so "requests" and
        # "mutations" are now different numbers — counting only requests
        # would let batching silently inflate the per-request economics
        # bench.py reports. ``node_write_requests`` counts node-write API
        # calls (patch/replace, incl. 429-rejected ones — the server
        # still paid for them); ``node_write_mutations`` counts the
        # logical units those calls carried (label keys, annotation
        # keys, a taint-list change, a spec field).
        self.node_write_requests = 0
        self.node_write_mutations = 0
        #: node READ round trips (get_node + list_nodes): the number
        #: the informer refactor (ISSUE 11) drives to zero on the
        #: steady-state scan path — tests/test_shard.py pins it.
        #: peek_node_label is measurement surface and stays uncounted.
        self.node_read_requests = 0
        #: when set, idle watches emit BOOKMARK events at this cadence
        #: (like a real API server with allowWatchBookmarks), letting
        #: clients keep their resourceVersion current through
        #: other-object churn
        self.bookmark_every_s: Optional[float] = None
        #: core/v1 Events recorded via create_event: an append-ordered
        #: flat list (each event carries metadata.namespace)
        self.cluster_events: List[dict] = []
        #: cluster-scoped custom resources, keyed (group, plural, name).
        #: version is deliberately not part of the key: the fake serves
        #: one storage version, like a real API server does
        self._customs: Dict[Tuple[str, str, str], dict] = {}
        #: watch history for custom resources: (rv, type, group, plural,
        #: snapshot) — separate from the node history so node churn
        #: can't 410 a policy watcher
        self._custom_events: List[Tuple[int, str, str, str, dict]] = []
        #: coordination.k8s.io/v1 Leases, keyed (namespace, name)
        self._leases: Dict[Tuple[str, str], dict] = {}

    # ------------------------------------------------------------ helpers
    @property
    def _compact_chunk(self) -> int:
        """Compaction slack: histories trim only once they exceed
        limit + chunk (then back down to limit), amortizing the slice
        over a quarter-limit of writes instead of paying O(limit) per
        write. Derived from the LIVE limit so tests that shrink
        ``_history_limit`` get proportionally tight compaction."""
        return max(1, self._history_limit // 4)

    def _bump(self, obj: dict) -> None:
        self._rv += 1
        obj["metadata"]["resourceVersion"] = str(self._rv)

    def _record(self, etype: str, node: dict) -> None:
        self._events.append(
            _WatchEvent(self._rv, etype, copy.deepcopy(node))
        )
        self._event_rvs.append(self._rv)
        if len(self._events) > self._history_limit + self._compact_chunk:
            # chunked resourceVersion-window compaction: pay one slice
            # per chunk of writes, not per write
            self._events = self._events[-self._history_limit:]
            self._event_rvs = self._event_rvs[-self._history_limit:]
        self._lock.notify_all()

    def _events_after(self, rv: int) -> List[_WatchEvent]:
        """Retained node events with rv strictly greater than ``rv``
        (caller holds _lock). Binary search over the parallel rv list:
        a fleet of watchers rescanning the whole history linearly on
        every wakeup was O(history x watchers x writes) — the fake API
        server's own scaling wall at 256 live replicas."""
        i = bisect.bisect_right(self._event_rvs, rv)
        return self._events[i:]

    # ------------------------------------------------------- test surface
    def add_node(self, node: dict) -> dict:
        with self._lock:
            self._bump(node)
            self._nodes[node["metadata"]["name"]] = node
            self._record("ADDED", node)
            return copy.deepcopy(node)

    def add_pod(self, pod: dict) -> dict:
        with self._lock:
            self._bump(pod)
            self._pods[(pod["metadata"]["namespace"], pod["metadata"]["name"])] = pod
            return copy.deepcopy(pod)

    def compact_watch_history(self) -> None:
        """Drop all retained events: any resume from an old rv now 410s."""
        with self._lock:
            self._events = []
            self._event_rvs = []

    @property
    def latest_rv(self) -> str:
        with self._lock:
            return str(self._rv)

    def node_write_stats(self) -> dict:
        """Snapshot of the node-write accounting: HTTP-round-trip
        ``requests`` vs the ``mutations`` (logical label/annotation/
        taint/spec units) they carried. The gap between the two IS the
        coalescing win — bench.py reports both."""
        with self._lock:
            return {
                "requests": self.node_write_requests,
                "mutations": self.node_write_mutations,
            }

    def peek_node_label(self, name: str, key: str):
        """Measurement-only read of one node label WITHOUT the full-node
        deepcopy ``get_node`` pays: bench/simlab convergence pollers call
        this at tens of Hz per node, and deepcopying evidence-laden node
        objects inside the store lock was measurement load distorting
        the system under test."""
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                raise ApiException(404, f"node {name} not found")
            return (node["metadata"].get("labels") or {}).get(key)

    def _fault_gate(self) -> None:
        """Region-fault front door, called at the ENTRY of every API
        verb BEFORE the lock: latency skew sleeps here (out of lock —
        a slow region must not serialize its own watchers), then a
        blackout answers 503 like a dead regional control plane."""
        delay = self.response_delay_s
        if delay:
            time.sleep(delay)
        if self.blackout:
            raise ApiException(503, "injected regional API blackout")

    def _check_node_write_fault(self) -> None:
        """429 the next N node writes when armed (caller holds _lock)."""
        self.node_write_requests += 1
        if self.fail_next_node_writes > 0:
            self.fail_next_node_writes -= 1
            raise ApiException(429, "injected node-write overload")

    @staticmethod
    def _mutation_units(old: dict, new: dict) -> int:
        """Logical mutation units between two node objects: changed/
        removed label keys + annotation keys + 1 per changed spec
        field. resourceVersion/managed metadata moves don't count."""
        units = 0
        for field in ("labels", "annotations"):
            a = (old.get("metadata") or {}).get(field) or {}
            b = (new.get("metadata") or {}).get(field) or {}
            keys = set(a) | set(b)
            units += sum(1 for k in keys if a.get(k) != b.get(k))
        old_spec = old.get("spec") or {}
        new_spec = new.get("spec") or {}
        for k in set(old_spec) | set(new_spec):
            if old_spec.get(k) != new_spec.get(k):
                units += 1
        return units

    # ------------------------------------------------------------- nodes
    def get_node(self, name: str) -> dict:
        self._fault_gate()
        with self._lock:
            self.node_read_requests += 1
            node = self._nodes.get(name)
            if node is None:
                raise ApiException(404, f"node {name} not found")
            return copy.deepcopy(node)

    def list_nodes(self, label_selector: Optional[str] = None) -> List[dict]:
        self._fault_gate()
        with self._lock:
            self.node_read_requests += 1
            if self.fail_next_lists > 0:
                self.fail_next_lists -= 1
                raise ApiException(429, "injected list overload")
            return [
                copy.deepcopy(n)
                for n in self._nodes.values()
                if match_selector(n["metadata"].get("labels", {}), label_selector)
            ]

    def list_nodes_page(
        self,
        label_selector: Optional[str] = None,
        limit: Optional[int] = None,
        cont: Optional[str] = None,
    ) -> Tuple[List[dict], Optional[str]]:
        """Chunked LIST: (items, continue_token). Name-ordered like the
        real API server; the token encodes the resume position."""
        return _paginate(self.list_nodes(label_selector), limit, cont)

    def set_node_labels_direct(
        self, name: str,
        labels: Dict[str, Optional[str]],
        annotations: Optional[Dict[str, Optional[str]]] = None,
    ) -> dict:
        """Operator hand-of-god label write for scenario/bench drivers:
        bypasses write-fault injection and the write accounting (it is
        the scenario's INPUT, not system-under-test traffic) while
        still bumping the resourceVersion and emitting a watch event
        like any real write — a driver that wrote through the faulted
        path would soak the very storm it scripted. ``annotations``
        ride the same write (the simlab driver stamps its cc.trace
        context exactly like a real controller: in ONE write with the
        desired label)."""
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                raise ApiException(404, f"node {name} not found")
            meta: Dict[str, object] = {"labels": labels}
            if annotations:
                meta["annotations"] = annotations
            merged = merge_patch(node, {"metadata": meta})
            merged["metadata"]["name"] = name
            self._nodes[name] = merged
            self._bump(merged)
            self._record("MODIFIED", merged)
            return copy.deepcopy(merged)

    def patch_node(self, name: str, patch: dict) -> dict:
        self._fault_gate()
        if self.patch_delay_s:
            time.sleep(self.patch_delay_s)
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                raise ApiException(404, f"node {name} not found")
            self._check_node_write_fault()
            merged = merge_patch(node, patch)
            merged["metadata"]["name"] = name  # name is immutable
            self.node_write_mutations += self._mutation_units(node, merged)
            self._nodes[name] = merged
            self._bump(merged)
            self._record("MODIFIED", merged)
            return copy.deepcopy(merged)

    def replace_node(self, name: str, node: dict) -> dict:
        self._fault_gate()
        with self._lock:
            cur = self._nodes.get(name)
            if cur is None:
                raise ApiException(404, f"node {name} not found")
            self._check_node_write_fault()
            if node["metadata"].get("resourceVersion") != cur["metadata"]["resourceVersion"]:
                raise ConflictError(
                    f"rv {node['metadata'].get('resourceVersion')} != "
                    f"{cur['metadata']['resourceVersion']}"
                )
            new = copy.deepcopy(node)
            new["metadata"]["name"] = name
            self.node_write_mutations += self._mutation_units(cur, new)
            self._nodes[name] = new
            self._bump(new)
            self._record("MODIFIED", new)
            return copy.deepcopy(new)

    # ------------------------------------------------------------- leases
    def get_lease(self, namespace: str, name: str) -> dict:
        self._fault_gate()
        with self._lock:
            lease = self._leases.get((namespace, name))
            if lease is None:
                raise ApiException(404, f"lease {namespace}/{name} not found")
            return copy.deepcopy(lease)

    def create_lease(self, namespace: str, lease: dict) -> dict:
        self._fault_gate()
        with self._lock:
            name = lease["metadata"]["name"]
            if (namespace, name) in self._leases:
                raise ApiException(
                    409, f"lease {namespace}/{name} already exists"
                )
            new = copy.deepcopy(lease)
            new["metadata"]["namespace"] = namespace
            self._bump(new)
            self._leases[(namespace, name)] = new
            return copy.deepcopy(new)

    def replace_lease(self, namespace: str, name: str,
                      lease: dict) -> dict:
        self._fault_gate()
        with self._lock:
            cur = self._leases.get((namespace, name))
            if cur is None:
                raise ApiException(404, f"lease {namespace}/{name} not found")
            if (lease["metadata"].get("resourceVersion")
                    != cur["metadata"]["resourceVersion"]):
                # the CAS two would-be leaders race on: exactly one
                # replace lands per observed rv
                raise ConflictError(
                    f"rv {lease['metadata'].get('resourceVersion')} != "
                    f"{cur['metadata']['resourceVersion']}"
                )
            new = copy.deepcopy(lease)
            new["metadata"]["name"] = name
            new["metadata"]["namespace"] = namespace
            self._bump(new)
            self._leases[(namespace, name)] = new
            return copy.deepcopy(new)

    # -------------------------------------------------------------- pods
    def list_pods(
        self,
        namespace: str,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
    ) -> List[dict]:
        node_name = None
        if field_selector:
            for term in field_selector.split(","):
                if term.startswith("spec.nodeName="):
                    node_name = term.split("=", 1)[1]
        with self._lock:
            out = []
            for (ns, _), pod in self._pods.items():
                if ns != namespace:
                    continue
                if not match_selector(pod["metadata"].get("labels", {}), label_selector):
                    continue
                if node_name and pod["spec"].get("nodeName") != node_name:
                    continue
                out.append(copy.deepcopy(pod))
            return out

    def list_pods_page(
        self,
        namespace: str,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
        limit: Optional[int] = None,
        cont: Optional[str] = None,
    ) -> Tuple[List[dict], Optional[str]]:
        return _paginate(
            self.list_pods(namespace, label_selector, field_selector), limit, cont
        )

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            if (namespace, name) not in self._pods:
                raise ApiException(404, f"pod {namespace}/{name} not found")
            del self._pods[(namespace, name)]
            self._lock.notify_all()

    def evict_pod(self, namespace: str, name: str) -> None:
        self._fault_gate()
        with self._lock:
            if (namespace, name) in self.pdb_blocked:
                raise ApiException(429, "Cannot evict pod: PodDisruptionBudget")
            if (namespace, name) not in self._pods:
                raise ApiException(404, f"pod {namespace}/{name} not found")
            del self._pods[(namespace, name)]
            self._lock.notify_all()

    def create_event(self, namespace: str, event: dict) -> dict:
        self._fault_gate()
        with self._lock:
            stored = copy.deepcopy(event)
            body_ns = stored.get("metadata", {}).get("namespace")
            if body_ns is not None and body_ns != namespace:
                # real apiserver rule: event.namespace must match the
                # request path's namespace
                raise ApiException(
                    400,
                    f"the namespace of the object ({body_ns}) does not "
                    f"match the namespace on the request ({namespace})",
                )
            stored.setdefault("metadata", {})["namespace"] = namespace
            self._rv += 1
            stored["metadata"]["resourceVersion"] = str(self._rv)
            self.cluster_events.append(stored)
            if len(self.cluster_events) > (self._history_limit
                                           + self._compact_chunk):
                # same chunked bound as the watch history: a long
                # simlab run's Event stream must not grow memory
                # forever (ISSUE 11 satellite)
                self.cluster_events = (
                    self.cluster_events[-self._history_limit:]
                )
            return copy.deepcopy(stored)

    def list_events(self, namespace: str) -> List[dict]:
        with self._lock:
            return [
                copy.deepcopy(e) for e in self.cluster_events
                if e["metadata"]["namespace"] == namespace
            ]

    # ------------------------------------------------- custom resources
    def add_custom(self, group: str, plural: str, obj: dict) -> dict:
        """Create a cluster-scoped custom resource (test surface, the
        ``kubectl apply`` analog)."""
        with self._lock:
            stored = copy.deepcopy(obj)
            stored.setdefault("metadata", {}).setdefault("generation", 1)
            self._bump(stored)
            self._customs[(group, plural, stored["metadata"]["name"])] = stored
            self._record_custom("ADDED", group, plural, stored)
            return copy.deepcopy(stored)

    def list_cluster_custom(
        self, group: str, version: str, plural: str
    ) -> List[dict]:
        self._fault_gate()
        with self._lock:
            return sorted(
                (
                    copy.deepcopy(o)
                    for (g, p, _), o in self._customs.items()
                    if g == group and p == plural
                ),
                key=lambda o: o["metadata"]["name"],
            )

    def get_cluster_custom(
        self, group: str, version: str, plural: str, name: str
    ) -> dict:
        self._fault_gate()
        with self._lock:
            obj = self._customs.get((group, plural, name))
            if obj is None:
                raise ApiException(
                    404, f"{plural}.{group} {name!r} not found"
                )
            return copy.deepcopy(obj)

    def patch_cluster_custom(
        self,
        group: str,
        version: str,
        plural: str,
        name: str,
        patch: dict,
        subresource: Optional[str] = None,
    ) -> dict:
        self._fault_gate()
        with self._lock:
            cur = self._customs.get((group, plural, name))
            if cur is None:
                raise ApiException(
                    404, f"{plural}.{group} {name!r} not found"
                )
            if subresource == "status":
                # status subresource: only .status moves; spec/metadata in
                # the patch body are ignored and generation never bumps
                # (the real API server's subresource contract)
                merged = merge_patch(
                    cur, {"status": patch.get("status", {})}
                )
            elif subresource:
                raise ApiException(
                    404, f"subresource {subresource!r} not served"
                )
            else:
                # main resource: status in the patch is ignored (it has a
                # subresource), and a spec change bumps the generation —
                # observedGeneration bookkeeping depends on this
                body = {k: v for k, v in patch.items() if k != "status"}
                merged = merge_patch(cur, body)
                if merged.get("spec") != cur.get("spec"):
                    gen = merged["metadata"].get("generation", 1)
                    merged["metadata"]["generation"] = gen + 1
            merged["metadata"]["name"] = name
            self._customs[(group, plural, name)] = merged
            self._bump(merged)
            self._record_custom("MODIFIED", group, plural, merged)
            return copy.deepcopy(merged)

    def _record_custom(self, etype: str, group: str, plural: str,
                       obj: dict) -> None:
        self._custom_events.append(
            (self._rv, etype, group, plural, copy.deepcopy(obj))
        )
        if len(self._custom_events) > (self._history_limit
                                       + self._compact_chunk):
            self._custom_events = self._custom_events[-self._history_limit:]
        self._lock.notify_all()

    def watch_cluster_custom(
        self,
        group: str,
        version: str,
        plural: str,
        resource_version: Optional[str] = None,
        timeout_s: int = 300,
    ) -> Iterator[Tuple[str, dict]]:
        """Watch one cluster-scoped CR collection; the same rv / replay
        / server-timeout semantics as watch_nodes. No 410 compaction
        model here (policy objects are few and slow-moving); a caller
        that falls behind simply re-lists."""
        self._fault_gate()
        deadline = time.monotonic() + timeout_s
        last_rv = int(resource_version) if resource_version is not None else None
        while True:
            if self.blackout:
                # sever in-flight CR watches too: a blacked-out region
                # streams nothing
                raise ApiException(503, "injected regional API blackout")
            with self._lock:
                if last_rv is None:
                    last_rv = self._rv
                pending = [
                    (rv, t, obj)
                    for (rv, t, g, p, obj) in self._custom_events
                    if rv > last_rv and g == group and p == plural
                ]
                if self._custom_events:
                    last_rv = max(last_rv, self._custom_events[-1][0])
                if not pending:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return
                    self._lock.wait(timeout=min(remaining, 0.5))
                    continue
            for rv, etype, obj in pending:
                yield etype, copy.deepcopy(obj)

    # ------------------------------------------------------------- watch
    def _watch_stream(
        self,
        name: Optional[str],
        resource_version: Optional[str],
        timeout_s: float,
        allow_bookmarks: bool,
    ) -> Iterator[Tuple[str, object]]:
        """Shared watch core: yields ``("EVENT", _WatchEvent)`` and
        ``("BOOKMARK", node_dict)`` — :meth:`watch_nodes` (clientset
        shape) and :meth:`watch_nodes_wire` (pre-encoded apiserver fan
        out) are thin views over it, so the rv/410/timeout semantics
        cannot drift between the two."""
        self._fault_gate()
        with self._lock:
            if self.fail_next_watches > 0:
                self.fail_next_watches -= 1
                raise ApiException(500, "injected watch failure")
        deadline = time.monotonic() + timeout_s
        last_rv = int(resource_version) if resource_version is not None else None
        last_bookmark = time.monotonic()
        establishing = True

        while True:
            if self.blackout:
                # sever the in-flight stream: a blacked-out region's
                # watchers see a broken watch and retry into the 503s
                raise ApiException(503, "injected regional API blackout")
            bookmark = None
            with self._lock:
                if last_rv is None:
                    # no rv: start from "now", like an unversioned k8s watch
                    last_rv = self._rv
                elif establishing:
                    # staleness is judged at watch establishment only: once
                    # streaming, this generator examines every event (even
                    # ones the name filter drops), so later compaction of
                    # already-examined history must not kill a live stream
                    oldest_retained = self._events[0].rv if self._events else self._rv + 1
                    if last_rv + 1 < oldest_retained and last_rv < self._rv:
                        # requested window fell out of history
                        raise ApiException(410, "too old resource version")
                establishing = False
                pending = [
                    ev
                    for ev in self._events_after(last_rv)
                    if name is None or ev.obj["metadata"]["name"] == name
                ]
                if self._events:
                    # everything currently retained has now been examined
                    last_rv = max(last_rv, self._events[-1].rv)
                if not pending:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return  # server-side watch timeout: clean stream end
                    if (
                        allow_bookmarks
                        and self.bookmark_every_s is not None
                        and time.monotonic() - last_bookmark
                        >= self.bookmark_every_s
                    ):
                        # fast-forward the client past churn it filtered
                        # out (other nodes, pods) so a reconnect from this
                        # rv stays inside retained history
                        last_bookmark = time.monotonic()
                        last_rv = self._rv
                        bookmark = {
                            "kind": "Node",
                            "apiVersion": "v1",
                            "metadata": {
                                "name": name or "",
                                "resourceVersion": str(self._rv),
                            },
                        }
                    else:
                        self._lock.wait(timeout=min(remaining, 0.5))
                        continue
            if bookmark is not None:
                yield "BOOKMARK", bookmark
                continue
            for ev in pending:
                last_rv = max(last_rv, ev.rv)
                yield "EVENT", ev

    def watch_nodes(
        self,
        name: Optional[str] = None,
        resource_version: Optional[str] = None,
        timeout_s: int = 300,
        allow_bookmarks: bool = True,
    ) -> Iterator[Tuple[str, dict]]:
        for kind, item in self._watch_stream(
            name, resource_version, timeout_s, allow_bookmarks
        ):
            if kind == "BOOKMARK":
                yield "BOOKMARK", item  # type: ignore[misc]
            else:
                yield item.etype, copy.deepcopy(item.obj)  # type: ignore[union-attr]

    def watch_nodes_wire(
        self,
        name: Optional[str] = None,
        resource_version: Optional[str] = None,
        timeout_s: float = 300,
        allow_bookmarks: bool = True,
    ) -> Iterator[bytes]:
        """The apiserver's fan-out path: NDJSON watch lines with the
        per-event encode paid ONCE fleet-wide (``_WatchEvent.wire``),
        instead of once per watcher per event. Bookmarks are per-stream
        (they carry the stream's name) and stay encoded ad hoc."""
        for kind, item in self._watch_stream(
            name, resource_version, timeout_s, allow_bookmarks
        ):
            if kind == "BOOKMARK":
                yield json.dumps(
                    {"type": "BOOKMARK", "object": item}
                ).encode() + b"\n"
            else:
                yield item.wire()  # type: ignore[union-attr]
