"""A real HTTP API server fronting a FakeKube store.

Speaks enough of the Kubernetes REST wire protocol for every client in
this repo — the Python HttpKubeClient, the C++ native agent, the bash
engine (via curl) — to run end-to-end without a cluster. This is the
kind-cluster stand-in for BASELINE config 1 and the integration-test /
bench substrate (SURVEY.md §4's "fake k8s API" requirement).

Endpoints:

- ``GET    /api/v1/nodes``               (list; labelSelector; watch=true)
- ``GET    /api/v1/nodes/{name}``
- ``PATCH  /api/v1/nodes/{name}``        (application/merge-patch+json)
- ``PUT    /api/v1/nodes/{name}``        (optimistic replace -> 409)
- ``GET    /api/v1/namespaces/{ns}/pods``
- ``DELETE /api/v1/namespaces/{ns}/pods/{name}``
- ``POST   /api/v1/namespaces/{ns}/pods/{name}/eviction``
- ``POST   /api/v1/namespaces/{ns}/events``
- ``GET    /api/v1/namespaces/{ns}/events``
- ``GET    /apis/{group}/{ver}/{plural}``          (cluster-scoped CRs; watch=true)
- ``GET    /apis/{group}/{ver}/{plural}/{name}``
- ``PATCH  /apis/{group}/{ver}/{plural}/{name}[/status]``
- ``GET    /apis/coordination.k8s.io/v1/namespaces/{ns}/leases/{name}``
- ``POST   /apis/coordination.k8s.io/v1/namespaces/{ns}/leases``
- ``PUT    /apis/coordination.k8s.io/v1/namespaces/{ns}/leases/{name}``  (CAS -> 409)

Watch responses are newline-delimited JSON event streams, ending when the
``timeoutSeconds`` window elapses (clean EOF), or a single ERROR event for
410, exactly as a real API server behaves.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpu_cc_manager.k8s.client import ApiException
from tpu_cc_manager.k8s.fake import FakeKube

log = logging.getLogger("tpu-cc-manager.fake-apiserver")


def _list_obj(kind: str, items: list, cont: Optional[str]) -> dict:
    # A real apiserver omits TypeMeta (kind/apiVersion) from list items —
    # only the List object itself carries it. Serve the same shape so
    # clients that grep or parse items are tested against real wire
    # format (a grep for '"kind":"Pod"' must count 0 here, as it would
    # in production).
    items = [{k: v for k, v in it.items() if k not in ("kind", "apiVersion")}
             for it in items]
    out = {"kind": kind, "apiVersion": "v1", "items": items, "metadata": {}}
    if cont:
        out["metadata"]["continue"] = cont
    return out


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    store: FakeKube  # set by server factory
    required_token: Optional[str] = None  # when set, reject non-bearers 401

    # silence default stderr access logging
    def log_message(self, fmt, *args):  # pragma: no cover
        pass

    def _authorized(self) -> bool:
        """Bearer-token gate, enabled by FakeApiServer(required_token=...).
        Lets tests prove the exec-credential/kubeconfig auth path
        end-to-end over the wire."""
        if self.required_token is None:
            return True
        if self.headers.get("Authorization") == f"Bearer {self.required_token}":
            return True
        self._send_error_status(ApiException(401, "Unauthorized"))
        return False

    # ---------------------------------------------------------- plumbing
    def _send_json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_error_status(self, e: ApiException) -> None:
        self._send_json(
            e.status,
            {
                "kind": "Status",
                "apiVersion": "v1",
                "status": "Failure",
                "message": e.reason,
                "code": e.status,
            },
        )

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0) or 0)
        if not length:
            return {}
        return json.loads(self.rfile.read(length))

    def _parts(self):
        parsed = urllib.parse.urlparse(self.path)
        return parsed.path.strip("/").split("/"), dict(
            urllib.parse.parse_qsl(parsed.query)
        )

    # ------------------------------------------------------------- verbs
    def do_GET(self):
        if not self._authorized():
            return
        parts, q = self._parts()
        try:
            if parts[:3] == ["api", "v1", "nodes"]:
                if len(parts) == 4:
                    return self._send_json(200, self.store.get_node(parts[3]))
                if q.get("watch") == "true":
                    return self._stream_watch(q)
                items, cont = self.store.list_nodes_page(
                    q.get("labelSelector"),
                    limit=int(q["limit"]) if q.get("limit") else None,
                    cont=q.get("continue"),
                )
                return self._send_json(200, _list_obj("NodeList", items, cont))
            if (
                len(parts) >= 5
                and parts[:3] == ["api", "v1", "namespaces"]
                and parts[4] == "pods"
            ):
                ns = parts[3]
                if len(parts) == 5:
                    items, cont = self.store.list_pods_page(
                        ns,
                        q.get("labelSelector"),
                        q.get("fieldSelector"),
                        limit=int(q["limit"]) if q.get("limit") else None,
                        cont=q.get("continue"),
                    )
                    return self._send_json(200, _list_obj("PodList", items, cont))
            if (
                len(parts) == 5
                and parts[:3] == ["api", "v1", "namespaces"]
                and parts[4] == "events"
            ):
                return self._send_json(
                    200,
                    _list_obj("EventList",
                              self.store.list_events(parts[3]), None),
                )
            if (
                len(parts) == 7
                and parts[1] == "coordination.k8s.io"
                and parts[3] == "namespaces"
                and parts[5] == "leases"
            ):
                return self._send_json(
                    200, self.store.get_lease(parts[4], parts[6])
                )
            if parts[0] == "apis" and len(parts) == 4:
                group, ver, plural = parts[1], parts[2], parts[3]
                if q.get("watch") == "true":
                    return self._stream_custom_watch(group, ver, plural, q)
                items = self.store.list_cluster_custom(group, ver, plural)
                return self._send_json(200, _list_obj("List", items, None))
            if parts[0] == "apis" and len(parts) == 5:
                return self._send_json(
                    200,
                    self.store.get_cluster_custom(
                        parts[1], parts[2], parts[3], parts[4]
                    ),
                )
            return self._send_error_status(ApiException(404, f"no route {self.path}"))
        except ApiException as e:
            return self._send_error_status(e)

    def do_PATCH(self):
        if not self._authorized():
            return
        parts, _ = self._parts()
        try:
            if len(parts) == 4 and parts[:3] == ["api", "v1", "nodes"]:
                return self._send_json(
                    200, self.store.patch_node(parts[3], self._read_body())
                )
            if parts[0] == "apis" and len(parts) in (5, 6):
                sub = parts[5] if len(parts) == 6 else None
                return self._send_json(
                    200,
                    self.store.patch_cluster_custom(
                        parts[1], parts[2], parts[3], parts[4],
                        self._read_body(), subresource=sub,
                    ),
                )
            return self._send_error_status(ApiException(404, f"no route {self.path}"))
        except ApiException as e:
            return self._send_error_status(e)

    def do_PUT(self):
        if not self._authorized():
            return
        parts, _ = self._parts()
        try:
            if len(parts) == 4 and parts[:3] == ["api", "v1", "nodes"]:
                return self._send_json(
                    200, self.store.replace_node(parts[3], self._read_body())
                )
            if (
                len(parts) == 7
                and parts[1] == "coordination.k8s.io"
                and parts[3] == "namespaces"
                and parts[5] == "leases"
            ):
                return self._send_json(
                    200,
                    self.store.replace_lease(
                        parts[4], parts[6], self._read_body()
                    ),
                )
            return self._send_error_status(ApiException(404, f"no route {self.path}"))
        except ApiException as e:
            return self._send_error_status(e)

    def do_DELETE(self):
        if not self._authorized():
            return
        parts, _ = self._parts()
        try:
            if (
                len(parts) == 6
                and parts[:3] == ["api", "v1", "namespaces"]
                and parts[4] == "pods"
            ):
                self.store.delete_pod(parts[3], parts[5])
                return self._send_json(200, {"kind": "Status", "status": "Success"})
            return self._send_error_status(ApiException(404, f"no route {self.path}"))
        except ApiException as e:
            return self._send_error_status(e)

    def do_POST(self):
        if not self._authorized():
            return
        parts, _ = self._parts()
        try:
            if (
                len(parts) == 7
                and parts[:3] == ["api", "v1", "namespaces"]
                and parts[4] == "pods"
                and parts[6] == "eviction"
            ):
                self._read_body()
                self.store.evict_pod(parts[3], parts[5])
                return self._send_json(201, {"kind": "Status", "status": "Success"})
            if (
                len(parts) == 5
                and parts[:3] == ["api", "v1", "namespaces"]
                and parts[4] == "events"
            ):
                return self._send_json(
                    201, self.store.create_event(parts[3], self._read_body())
                )
            if (
                len(parts) == 6
                and parts[1] == "coordination.k8s.io"
                and parts[3] == "namespaces"
                and parts[5] == "leases"
            ):
                return self._send_json(
                    201,
                    self.store.create_lease(parts[4], self._read_body()),
                )
            return self._send_error_status(ApiException(404, f"no route {self.path}"))
        except ApiException as e:
            return self._send_error_status(e)

    # ------------------------------------------------------------- watch
    def _stream_custom_watch(self, group: str, ver: str, plural: str,
                             q: dict) -> None:
        self._stream_events(
            lambda: self.store.watch_cluster_custom(
                group, ver, plural,
                resource_version=q.get("resourceVersion"),
                timeout_s=float(q.get("timeoutSeconds", "300")),
            )
        )

    def _stream_watch(self, q: dict) -> None:
        name: Optional[str] = None
        fs = q.get("fieldSelector", "")
        if fs.startswith("metadata.name="):
            name = fs.split("=", 1)[1]
        # node watches ride the store's pre-encoded fan-out path: each
        # event is serialized once fleet-wide (_WatchEvent.wire), not
        # once per watcher — the O(history x watchers) encode cost was
        # the fake apiserver's wall at four-digit replica counts
        self._stream_events(
            lambda: self.store.watch_nodes_wire(
                name=name,
                resource_version=q.get("resourceVersion"),
                timeout_s=float(q.get("timeoutSeconds", "300")),
                allow_bookmarks=q.get("allowWatchBookmarks") == "true",
            ),
            wire=True,
        )

    def _stream_events(self, iter_factory, wire: bool = False) -> None:
        """Serve one watch stream (chunked NDJSON, ERROR event on
        ApiException, clean EOF at timeout) from any event iterator.
        ``wire=True`` means the iterator already yields encoded NDJSON
        lines (the shared-encode fan-out path)."""
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def _chunk(data: bytes) -> None:
            self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        try:
            try:
                if wire:
                    for line in iter_factory():
                        _chunk(line)
                else:
                    for etype, obj in iter_factory():
                        _chunk(
                            json.dumps({"type": etype, "object": obj}).encode()
                            + b"\n"
                        )
            except ApiException as e:
                err = {
                    "type": "ERROR",
                    "object": {
                        "kind": "Status",
                        "code": e.status,
                        "reason": "Expired" if e.status == 410
                        else "InternalError",
                        "message": e.reason,
                    },
                }
                _chunk(json.dumps(err).encode() + b"\n")
            _chunk(b"")  # terminating chunk
        except (BrokenPipeError, ConnectionResetError):  # client went away
            return


class _ApiHTTPServer(ThreadingHTTPServer):
    # a 32-node pool opening watch streams at once overflows the
    # default listen(5) backlog -> connection resets
    request_queue_size = 256

    def handle_error(self, request, client_address):
        """Client-gone at the accept/readline layer (before or between
        requests) must not print socketserver's full traceback into a
        green smoke log — the in-handler suppression in _stream_events
        only covers disconnects DURING a response. Anything else still
        gets one loud line."""
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            return  # benign: a client hung up mid-handshake/idle
        log.warning("request from %s failed: %s: %s", client_address,
                    type(exc).__name__, exc)


class FakeApiServer:
    """Owns a ThreadingHTTPServer bound to 127.0.0.1:<port> over a FakeKube."""

    def __init__(
        self,
        store: Optional[FakeKube] = None,
        port: int = 0,
        required_token: Optional[str] = None,
        tls_cert: Optional[str] = None,
        tls_key: Optional[str] = None,
    ):
        self.store = store or FakeKube()
        handler = type(
            "BoundHandler",
            (_Handler,),
            {"store": self.store, "required_token": required_token},
        )
        self.httpd = _ApiHTTPServer(("127.0.0.1", port), handler)
        self.tls = bool(tls_cert)
        if tls_cert:
            # serve real HTTPS (the native agent's direct-TLS path is
            # integration-tested against this)
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key or tls_cert)
            self.httpd.socket = ctx.wrap_socket(
                self.httpd.socket, server_side=True
            )
        self.httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def url(self) -> str:
        scheme = "https" if self.tls else "http"
        return f"{scheme}://127.0.0.1:{self.port}"

    def start(self) -> "FakeApiServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="fake-apiserver", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def __enter__(self) -> "FakeApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
