"""Asyncio kube I/O core — multiplexed, pipelined node reads/writes/watches.

ROADMAP item 4's "next 10x" (ISSUE 13): the coalescing layer
(`k8s/batch.py`) got a steady-state flip down to two node writes, but
those writes still ride Python threads blocking one-request-per-
connection on a contended API server — BENCH_NOTES r03 established
that API round-trip *queueing*, not device work, is the hot path. This
module replaces the thread-per-request model with ONE event loop
multiplexing every request a process makes over a small set of
persistent, **pipelined** HTTP/1.1 connections:

- at most ``TPU_CC_KUBE_CONNS`` connections (default 8), dialed lazily
  and kept warm — concurrent writers beyond the connection budget
  QUEUE on the per-connection window, they never error and never open
  unbounded sockets;
- each connection carries a bounded in-flight window
  (``TPU_CC_KUBE_INFLIGHT``, default 4): up to that many requests are
  written before the first response returns, and HTTP/1.1's in-order
  response rule matches them back FIFO (``_Conn._inflight``);
- the sync client's **exactly-once replay contract is preserved**: a
  request whose connection died before sending ANY response bytes for
  it, on a connection that had already served at least one response
  (the stale keep-alive race — ``BadStatusLine`` in the threaded
  client), replays exactly once on a FRESH dedicated dial; a request
  with partial response bytes, or any failure on a fresh connection,
  is terminal (``ApiException(0)``) because the server may already
  have executed it — a merge patch can never double-apply. A request
  still *queued* when its connection died was never written, so it
  re-dispatches freely (that is not a replay; nothing left the
  process);
- long-lived watch streams get DEDICATED connections (HTTP/1.1 cannot
  interleave an unbounded chunked response with pipelined requests);
  they are counted in ``stats()`` but live outside the request pool;
- client-side flow control (QPS/burst) keeps the sync client's token-
  bucket semantics, awaited with ``asyncio.sleep`` so a throttled
  request parks its coroutine instead of a thread;
- every completed request reports its round-trip seconds (queue wait
  included — the number under OFFERED load, which is what the bench's
  ``flip_write_rtt_p50_s`` axis measures) to ``add_rtt_observer``
  callbacks.

Synchronous callers (the agent, the engine, simlab replicas) use
:mod:`tpu_cc_manager.k8s.aio_bridge`'s ``SyncKubeFacade`` — one loop
thread per process, submit()/gather() — and keep their contracts
unchanged. Full contract: docs/io.md §"The async core".

Known delta vs the threaded client (documented in docs/io.md): the
401 exec-credential invalidate-and-retry loop is not implemented here
— the async core targets the agent/simlab/bench hot paths, where auth
is a static bearer token or none; real-cluster exec-plugin flows keep
using ``HttpKubeClient``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
import urllib.parse
from collections import deque
from typing import (
    TYPE_CHECKING,
    AsyncIterator,
    Callable,
    Dict,
    List,
    Optional,
    Tuple,
)

from tpu_cc_manager.k8s.client import ApiException, ConflictError, KubeConfig

if TYPE_CHECKING:
    # runtime keeps the lazy in-function import (_build_ssl_ctx): ssl
    # loads certs/ciphers at import time and only TLS configs need it
    import ssl

log = logging.getLogger("tpu-cc-manager.k8s.aio")

#: connection budget (shared with the sync client's pool knob: one
#: process, one socket budget, whichever core it runs)
ENV_CONNS = "TPU_CC_KUBE_CONNS"
DEFAULT_CONNS = 8

#: per-connection pipelined in-flight window; 1 = strict request/
#: response lockstep per connection (the serial-equivalence setting
#: tests/test_engine_parallel.py pins span order against)
ENV_WINDOW = "TPU_CC_KUBE_INFLIGHT"
DEFAULT_WINDOW = 4

#: writer backlog admission bound: once every connection's window is
#: full, at most this many writers may QUEUE for a slot; the next one
#: gets an honest 429 and ``queue_rejected_total`` ticks
#: (``tpu_cc_kube_queue_rejected_total`` via obs.py). Unbounded was the
#: overload failure mode docs/io.md §"In-flight window contract" used
#: to admit to — saturation became memory growth and unbounded latency
#: instead of a rejection the caller can pace against (ROADMAP item 3).
ENV_QUEUE = "TPU_CC_KUBE_QUEUE"
DEFAULT_QUEUE = 256

#: socket-level write deadline: ``drain()`` on a wedged peer (zero TCP
#: window) would otherwise park the writer forever — before the
#: request's own read deadline is even armed
DRAIN_TIMEOUT_S = 30.0

#: TCP+TLS dial deadline (a blackholed endpoint fails the dial path's
#: fresh-connection contract instead of hanging it)
CONNECT_TIMEOUT_S = 10.0


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, "") or 0)
    except ValueError:
        return default
    return v if v > 0 else default


class _RedialNeeded(Exception):
    """The chosen connection died before this request's bytes were
    written: re-dispatch freely (no replay budget consumed)."""


class _StaleConnClosed(Exception):
    """Zero response bytes for a written request on a previously-
    serving connection — the BadStatusLine-analog, replayable once."""


class _AsyncTokenBucket:
    """The sync client's ``_TokenBucket`` semantics on the loop:
    refill at ``qps``, hold at most ``burst``, park (asyncio.sleep)
    until a token frees. Single-threaded by construction — only loop
    coroutines touch it."""

    def __init__(self, qps: float, burst: int) -> None:
        self.qps = float(qps)
        self.burst = max(1, int(burst))
        self._tokens = float(self.burst)
        self._updated = time.monotonic()

    async def acquire(self) -> float:
        waited = 0.0
        while True:
            now = time.monotonic()
            self._tokens = min(
                float(self.burst),
                self._tokens + (now - self._updated) * self.qps,
            )
            self._updated = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return waited
            wait = (1.0 - self._tokens) / self.qps
            await asyncio.sleep(wait)
            waited += wait


class _Pending:
    """One written-but-unanswered request on a connection."""

    __slots__ = ("method", "path", "future", "got_bytes", "replayed",
                 "sent_on_served")

    def __init__(self, method: str, path: str, replayed: bool) -> None:
        self.method = method
        self.path = path
        self.future: "asyncio.Future[Tuple[int, bytes]]" = (
            asyncio.get_running_loop().create_future()
        )
        self.got_bytes = False  # status line seen for THIS request
        self.replayed = replayed
        #: had the connection served >= 1 complete response AT WRITE
        #: TIME? Replay legality must be judged as of the moment the
        #: bytes left the process, not at failure time: a request
        #: pipelined onto a never-yet-served connection may have
        #: executed server-side even if a sibling's response arrived
        #: before the crash — replaying it could double-apply.
        self.sent_on_served = False


class _Conn:
    """One persistent pipelined connection: a write lock serializing
    request bytes, a FIFO of in-flight requests, a window semaphore
    bounding the pipeline depth, and a reader task matching responses
    back in order."""

    def __init__(self, client: "AsyncKubeClient", window: int) -> None:
        self.client = client
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._inflight: "deque[_Pending]" = deque()  # ccaudit: allow-unbounded-queue(per-conn FIFO holds at most `window` entries: every append happens under a window-semaphore slot, and admission past the windows is bounded by TPU_CC_KUBE_QUEUE)
        self.window = asyncio.Semaphore(window)
        self.write_lock = asyncio.Lock()
        self.served = 0  # complete responses received on this conn
        self.dead = False
        self.depth = 0  # queued + in-flight (dispatch heuristic)
        self._reader_task: Optional[asyncio.Task] = None

    async def ensure_open(self) -> None:
        if self.dead:
            raise _RedialNeeded()
        if self.writer is None:
            self.reader, self.writer = await self.client._dial()
            self._reader_task = asyncio.get_running_loop().create_task(
                self._read_loop()
            )

    def abort(self) -> None:
        """Hard-close (shutdown): the reader task observes EOF and
        fails the in-flight per the replay policy."""
        self.dead = True
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:  # ccaudit: allow-swallow(already tearing the socket down; close races are expected)
                pass

    def retire(self) -> None:
        """Stop routing NEW requests here but leave the socket open so
        in-flight pool-mates' responses still drain. Used when one
        pipelined request times out: hard-closing would terminally
        fail an innocent sibling whose write the server already
        executed and was answering. The reader keeps serving what
        remains; the server's idle keep-alive timeout reclaims the
        socket."""
        self.dead = True

    async def send(self, method: str, path: str,
                   payload: Optional[bytes], content_type: str,
                   replayed: bool) -> _Pending:
        """Write one request onto the pipeline; returns its pending
        slot. Raises _RedialNeeded when the conn died before these
        bytes went out (safe to re-dispatch)."""
        async with self.write_lock:
            await self.ensure_open()
            pending = _Pending(method, path, replayed)
            pending.sent_on_served = self.served > 0
            try:
                assert self.writer is not None
                self.writer.write(self.client._encode_request(
                    method, path, payload, content_type,
                    await self.client._auth_header(),
                ))
                # appended under the write lock, BEFORE drain: if drain
                # itself fails the bytes may be on the wire, so the
                # request must already be in the reader's FIFO for the
                # EOF policy to judge (never silently lost)
                self._inflight.append(pending)
                # TimeoutError ⊂ OSError: a wedged-peer drain lands in
                # the same bytes-may-be-on-the-wire branch below
                await asyncio.wait_for(self.writer.drain(),
                                       DRAIN_TIMEOUT_S)
            except (OSError, asyncio.IncompleteReadError) as e:
                self.abort()
                if pending not in self._inflight:
                    # never appended: nothing left the process
                    raise _RedialNeeded() from e
                # drain failed after buffering — the bytes may be on
                # the wire. The reader task may ALREADY have exited on
                # the same death (its EOF pass would then never judge
                # this pending), so run the policy here; it drains the
                # deque, making a second pass a no-op. No awaits in
                # _fail_inflight -> atomic on the loop, no double-set.
                self._fail_inflight()
            return pending

    # ----------------------------------------------------------- reading
    async def _read_loop(self) -> None:
        try:
            assert self.reader is not None
            while True:
                # ccaudit: allow-missing-deadline(reader-task idle read: between responses this SHOULD park indefinitely; every pending request carries its own wait_for deadline, and a wedged socket times those out and retires the conn)
                line = await self.reader.readline()
                if not line:
                    break  # EOF (idle close or mid-pipeline death)
                if not self._inflight:
                    log.warning("unsolicited bytes on pooled conn; closing")
                    break
                head = self._inflight[0]
                head.got_bytes = True
                status, headers = await self._read_head(line)
                body = await self.client._read_body(self.reader, headers)
                pending = self._inflight.popleft()
                self.served += 1
                if not pending.future.done():
                    pending.future.set_result((status, body))
                if headers.get("connection", "").lower() == "close":
                    break
        except (OSError, asyncio.IncompleteReadError, ValueError) as e:
            log.debug("pooled conn reader failed: %s", e)
        finally:
            self._fail_inflight()

    async def _read_head(self, status_line: bytes) -> Tuple[int, Dict[str, str]]:
        try:
            status = int(status_line.split(None, 2)[1])
        except (IndexError, ValueError):
            raise ValueError(f"bad status line {status_line!r}") from None
        headers: Dict[str, str] = {}
        assert self.reader is not None
        while True:
            # ccaudit: allow-missing-deadline(header read on the reader task: the request it serves carries its own wait_for deadline — a wedged mid-header socket times that request out and the conn is retired)
            line = await self.reader.readline()
            if not line:
                raise asyncio.IncompleteReadError(b"", None)
            if line in (b"\r\n", b"\n"):
                return status, headers
            k, _, v = line.decode("latin-1").partition(":")
            headers[k.strip().lower()] = v.strip()

    def _fail_inflight(self) -> None:
        self.dead = True
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:  # ccaudit: allow-swallow(the socket is already gone; close is best-effort)
                pass
        while self._inflight:
            p = self._inflight.popleft()
            if p.future.done():
                continue
            if p.got_bytes:
                # mid-response death: the server executed it; terminal
                p.future.set_exception(ApiException(
                    0, "transport error: connection closed mid-response"
                ))
            elif p.sent_on_served and not p.replayed:
                # zero response bytes AND the conn had served before
                # this request was WRITTEN: the stale keep-alive race —
                # replayable exactly once. (sent_on_served, not the
                # current served count: a sibling's response landing
                # after this request went out does not make this
                # request's execution state any more knowable.)
                p.future.set_exception(_StaleConnClosed())
            else:
                p.future.set_exception(ApiException(
                    0, "transport error: connection closed before any "
                       "response (never-served at write time — not "
                       "replayable)"
                ))


class AsyncKubeClient:
    """Event-loop kube client over pipelined persistent connections.

    Every coroutine here runs on ONE event loop (the bridge's loop
    thread for sync callers); all mutable state is loop-confined — no
    locks beyond the per-connection write lock that keeps pipelined
    request bytes contiguous.
    """

    LIST_PAGE_LIMIT = 500

    def __init__(self, config: KubeConfig,
                 max_conns: Optional[int] = None,
                 window: Optional[int] = None,
                 qps: Optional[float] = None,
                 burst: Optional[int] = None,
                 list_page_limit: Optional[int] = None,
                 max_queue: Optional[int] = None) -> None:
        self.config = config
        self.max_conns = max_conns or _env_int(ENV_CONNS, DEFAULT_CONNS)
        self.window = window or _env_int(ENV_WINDOW, DEFAULT_WINDOW)
        #: writer backlog admission bound (docs/io.md): the count of
        #: writers parked waiting for a window slot may never exceed
        #: this — the next writer past it gets an honest 429
        self.max_queue = max_queue or _env_int(ENV_QUEUE, DEFAULT_QUEUE)
        self._queued = 0
        self.list_page_limit = list_page_limit or self.LIST_PAGE_LIMIT
        self._conns: List[_Conn] = []
        self._ssl_ctx = None
        # serializes first-use context construction: without it two
        # concurrent first requests both see None, both build, and the
        # loser's dial binds a context the winner never sees
        # (ccaudit await-atomicity would flag exactly that shape)
        self._ssl_lock = asyncio.Lock()
        if qps is None:
            try:
                qps = float(os.environ.get("TPU_CC_KUBE_QPS", "") or 0)
            except ValueError:
                qps = 0.0
        self._bucket: Optional[_AsyncTokenBucket] = None
        if qps and qps > 0:
            self._bucket = _AsyncTokenBucket(qps, burst or int(2 * qps))
        # throttle visibility: same surface as the sync client so the
        # simlab runner/faults treat either core interchangeably
        self.throttle_waits = 0
        self.throttle_wait_s_total = 0.0
        self._throttle_observers: List[Callable[[float], None]] = []
        # per-request round-trip observers (queue wait included): the
        # bench's flip_write_rtt_p50_s axis feeds from here
        self._rtt_observers: List[Callable[[str, str, float], None]] = []
        # accounting (read via stats())
        self.dials_total = 0
        self.replays_total = 0
        self.requests_total = 0
        self.watches_total = 0
        #: writes refused at the admission gate (backlog full or the
        #: queue wait outliving the request's own deadline) — the
        #: overflow half of the TPU_CC_KUBE_QUEUE contract
        self.queue_rejected_total = 0
        self._queue_reject_observers: List[Callable[[], None]] = []

    # ------------------------------------------------------------- wiring
    def add_throttle_observer(self, fn: Callable[[float], None]) -> None:
        self._throttle_observers.append(fn)

    def add_rtt_observer(self, fn: Callable[[str, str, float], None]) -> None:
        """``fn(method, path, seconds)`` on every completed request —
        seconds span enqueue to response, so queueing under load is in
        the number (that is the point: it is the latency a flip WRITE
        actually experiences)."""
        self._rtt_observers.append(fn)

    def add_queue_reject_observer(self, fn: Callable[[], None]) -> None:
        """``fn()`` on every write refused at the backlog admission
        gate — obs.py's ``wire_queue_reject_observer`` hooks the
        ``tpu_cc_kube_queue_rejected_total`` counter here."""
        self._queue_reject_observers.append(fn)

    def set_qps(self, qps: float, burst: Optional[int] = None) -> None:
        if qps and qps > 0:
            self._bucket = _AsyncTokenBucket(qps, burst or int(2 * qps))
        else:
            self._bucket = None

    def stats(self) -> dict:
        # callable from any thread by design (the facade exposes it
        # without a bridge hop): every value is a single GIL-atomic
        # load of a monotonic counter — a stale snapshot is fine for
        # metrics, and nothing here is mutated
        return {
            "conns": len(self._conns),  # ccaudit: allow-loop-affinity(GIL-atomic len of a loop-written list; snapshot staleness is fine for metrics)
            "dials": self.dials_total,  # ccaudit: allow-loop-affinity(GIL-atomic read of a monotonic counter)
            "replays": self.replays_total,  # ccaudit: allow-loop-affinity(GIL-atomic read of a monotonic counter)
            "requests": self.requests_total,  # ccaudit: allow-loop-affinity(GIL-atomic read of a monotonic counter)
            "watches": self.watches_total,  # ccaudit: allow-loop-affinity(GIL-atomic read of a monotonic counter)
            "queue_rejected": self.queue_rejected_total,  # ccaudit: allow-loop-affinity(GIL-atomic read of a monotonic counter)
        }

    async def aclose(self) -> None:
        conns, self._conns = self._conns, []
        for c in conns:
            c.abort()

    # ----------------------------------------------------------- plumbing
    async def _dial(self) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter]:
        self.dials_total += 1
        ssl_ctx = None
        if self.config.use_tls:
            ssl_ctx = await self._ensure_ssl_ctx()
        # TimeoutError ⊂ OSError: a blackholed endpoint takes the same
        # terminal fresh-dial-failure path as a refused connection
        return await asyncio.wait_for(
            asyncio.open_connection(
                self.config.host, self.config.port, ssl=ssl_ctx
            ),
            CONNECT_TIMEOUT_S,
        )

    async def _ensure_ssl_ctx(self) -> "ssl.SSLContext":
        # double-checked under an asyncio.Lock: the executor hop below
        # is an interleaving point, so check-then-build must be atomic
        # across coroutines or concurrent first dials build twice
        async with self._ssl_lock:
            if self._ssl_ctx is None:
                # context construction reads CA/cert files off disk: off
                # the loop (our own blocking-in-async rule polices this
                # module)
                loop = asyncio.get_running_loop()
                self._ssl_ctx = await loop.run_in_executor(
                    None, self._build_ssl_ctx
                )
        return self._ssl_ctx

    def _build_ssl_ctx(self) -> "ssl.SSLContext":
        import ssl

        c = self.config
        ctx = ssl.create_default_context(cafile=c.ca_file)
        if c.insecure_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        pair = c.client_cert_pair()
        if pair:
            ctx.load_cert_chain(pair[0], pair[1])
        return ctx

    async def _auth_header(self) -> Optional[str]:
        token = self.config.token
        if token is None and self.config.exec_plugin is not None:
            # the exec plugin may fork a subprocess: never on the loop
            loop = asyncio.get_running_loop()
            token = await loop.run_in_executor(
                None, self.config.bearer_token
            )
        return f"Bearer {token}" if token else None

    def _encode_request(self, method: str, path: str,
                        payload: Optional[bytes], content_type: str,
                        auth: Optional[str]) -> bytes:
        lines = [
            f"{method} {path} HTTP/1.1",
            f"Host: {self.config.host}:{self.config.port}",
            "Accept: application/json",
        ]
        if auth:
            lines.append(f"Authorization: {auth}")
        if payload is not None:
            lines.append(f"Content-Type: {content_type}")
            lines.append(f"Content-Length: {len(payload)}")
        else:
            lines.append("Content-Length: 0")
        head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
        return head + (payload or b"")

    async def _read_body(self, reader: asyncio.StreamReader,
                         headers: Dict[str, str]) -> bytes:
        if headers.get("transfer-encoding", "").lower() == "chunked":
            out = b""
            async for chunk in self._iter_chunks(reader):
                out += chunk
            return out
        length = int(headers.get("content-length", "0") or 0)
        if length == 0:
            return b""
        # ccaudit: allow-missing-deadline(body read on the reader task/watch stream: bounded by the owning request's wait_for deadline or the watch's server-side timeoutSeconds)
        return await reader.readexactly(length)

    @staticmethod
    async def _iter_chunks(reader: asyncio.StreamReader) -> AsyncIterator[bytes]:
        while True:
            # ccaudit: allow-missing-deadline(chunk framing on the reader task: bounded by the owning request's wait_for deadline — the watch path wraps its own frame reads in wait_for separately)
            size_line = await reader.readline()
            if not size_line:
                raise asyncio.IncompleteReadError(b"", None)
            size = int(size_line.strip().split(b";")[0], 16)
            if size == 0:
                # ccaudit: allow-missing-deadline(trailing-CRLF read, same deadline ownership as the frame reads above)
                await reader.readline()  # trailing CRLF
                return
            # ccaudit: allow-missing-deadline(chunk payload read, same deadline ownership as the frame reads above)
            data = await reader.readexactly(size)
            await reader.readexactly(2)  # chunk CRLF  # ccaudit: allow-missing-deadline(chunk-CRLF read, same deadline ownership as the frame reads above)
            yield data

    # ---------------------------------------------------------- dispatch
    def _pick_conn(self) -> _Conn:
        """Least-depth live connection; dial a new one only while under
        the budget AND every live conn already has work in front of it.
        At the budget, callers QUEUE on the chosen conn's window."""
        live = [c for c in self._conns if not c.dead]
        self._conns = live
        idle = min(live, key=lambda c: c.depth) if live else None
        if idle is not None and idle.depth == 0:
            return idle
        if len(live) < self.max_conns:
            conn = _Conn(self, self.window)
            self._conns.append(conn)
            return conn
        assert idle is not None
        return idle

    async def _throttle(self) -> None:
        bucket = self._bucket
        waited = 0.0
        if bucket is not None:
            # ccaudit: allow-missing-deadline(token-bucket pacing: acquire sleeps exactly the computed refill interval — bounded by the bucket's own rate arithmetic, not by a peer)
            waited = await bucket.acquire()
            if waited > 0:
                self.throttle_waits += 1
                self.throttle_wait_s_total += waited
        for fn in self._throttle_observers:
            try:
                fn(waited)
            except Exception:  # ccaudit: allow-async-exception(observer isolation: a broken metrics hook must not fail the request; nothing is in flight here)
                log.debug("throttle observer failed", exc_info=True)

    async def _request(self, method: str, path: str,
                       body: Optional[dict] = None,
                       content_type: str = "application/json",
                       read_timeout: float = 30.0) -> dict:
        await self._throttle()
        payload = (json.dumps(body).encode()
                   if body is not None else None)
        t0 = time.monotonic()
        self.requests_total += 1
        try:
            status, data = await self._round_trip(
                method, path, payload, content_type, read_timeout
            )
        finally:
            rtt = time.monotonic() - t0
            for fn in self._rtt_observers:
                try:
                    fn(method, path, rtt)
                except Exception:  # ccaudit: allow-async-exception(observer isolation: the finally re-raises the round-trip's own failure; the hook must not mask it)
                    log.debug("rtt observer failed", exc_info=True)
        if status == 409:
            raise ConflictError(data.decode("utf-8", "replace")[:200])
        if status >= 400:
            raise ApiException(status, data.decode("utf-8", "replace")[:200])
        return json.loads(data) if data else {}

    def _reject_write(self, reason: str) -> None:
        self.queue_rejected_total += 1
        for fn in self._queue_reject_observers:
            try:
                fn()
            except Exception:  # ccaudit: allow-async-exception(observer isolation: a broken metrics hook must not mask the rejection being raised right below) # ccaudit: allow-swallow(observer isolation: the rejection itself is raised right below; the hook failure is logged)
                log.debug("queue reject observer failed", exc_info=True)
        raise ApiException(429, f"backlog full: {reason}")

    async def _admit(self, conn: _Conn, read_timeout: float) -> None:
        """Take a window slot, honestly. Past the windows at most
        ``max_queue`` writers may park; the next one — and any whose
        queue wait outlives its own read deadline — gets a 429 instead
        of an unbounded spot in line (the TPU_CC_KUBE_QUEUE contract,
        docs/io.md)."""
        if conn.window.locked() and self._queued >= self.max_queue:
            self._reject_write(
                f"{self._queued} writers already queued past the "
                f"window budget (TPU_CC_KUBE_QUEUE={self.max_queue})"
            )
        self._queued += 1
        try:
            # ccaudit: allow-raw-acquire(the admission gate acquires, _round_trip's finally releases: splitting them is what lets the queue wait carry a deadline while the slot spans the whole round trip)
            await asyncio.wait_for(conn.window.acquire(), read_timeout)
        except asyncio.TimeoutError:  # ccaudit: allow-async-exception(_reject_write unconditionally raises ApiException: this handler always propagates, it can never swallow the request path)
            # never acquired: wait_for cancelled the acquire (no slot
            # to release) — the wait itself outlived the deadline the
            # caller gave the whole request
            self._reject_write(
                f"no window slot freed in {read_timeout}s"
            )
        finally:
            # ccaudit: allow-await-atomicity(exact ticket count on one loop: the admission check runs atomically with the increment (no await between them), and each coroutine pairs exactly one increment with this one decrement — interleavings at the acquire await cannot tear it)
            self._queued -= 1

    async def _round_trip(self, method: str, path: str,
                          payload: Optional[bytes], content_type: str,
                          read_timeout: float) -> Tuple[int, bytes]:
        # ccaudit: allow-retry-discipline(_RedialNeeded re-dispatch: each turn retires a provably-stale pooled conn on which NOTHING reached the server; the pool holds at most max_conns stale conns, so this converges without pacing — it is dispatch, not congestion retry)
        while True:  # _RedialNeeded = never-written, re-dispatch freely
            conn = self._pick_conn()
            conn.depth += 1
            try:
                await self._admit(conn, read_timeout)
                try:
                    pending = await conn.send(
                        method, path, payload, content_type,
                        replayed=False,
                    )
                except _RedialNeeded:
                    conn.window.release()
                    continue
                except OSError as e:
                    # the DIAL itself failed: a fresh connection, so
                    # nothing executed server-side — terminal, like the
                    # sync client's fresh-dial failure
                    conn.window.release()
                    conn.abort()
                    raise ApiException(
                        0, f"transport error: {e}"
                    ) from e
                try:
                    result = await asyncio.wait_for(
                        pending.future, read_timeout
                    )
                except asyncio.TimeoutError:
                    # retire, don't abort: pool-mates pipelined behind
                    # (or ahead of) this request may be mid-response —
                    # killing the socket would terminally fail writes
                    # the server already executed. wait_for cancelled
                    # our future, so the reader skips our slot when
                    # (if) the response finally arrives.
                    conn.retire()
                    raise ApiException(
                        0, f"transport error: no response in "
                           f"{read_timeout}s"
                    ) from None
                except _StaleConnClosed:
                    # the exactly-once replay: a FRESH dedicated dial,
                    # never another possibly-stale pooled conn; failure
                    # there is terminal (_fail_inflight: served == 0)
                    self.replays_total += 1
                    result = await self._replay_fresh(
                        method, path, payload, content_type,
                        read_timeout,
                    )
                finally:
                    conn.window.release()
                return result
            finally:
                conn.depth -= 1

    async def _replay_fresh(self, method: str, path: str,
                            payload: Optional[bytes], content_type: str,
                            read_timeout: float) -> Tuple[int, bytes]:
        conn = _Conn(self, window=1)
        try:
            pending = await conn.send(
                method, path, payload, content_type, replayed=True
            )
            try:
                return await asyncio.wait_for(pending.future, read_timeout)
            except asyncio.TimeoutError:
                raise ApiException(
                    0, "transport error: replay got no response in "
                       f"{read_timeout}s"
                ) from None
        except (_RedialNeeded, OSError) as e:
            raise ApiException(
                0, f"transport error: replay connection failed: {e}"
            ) from e
        finally:
            conn.abort()

    # ------------------------------------------------------------- nodes
    async def get_node(self, name: str) -> dict:
        return await self._request("GET", f"/api/v1/nodes/{name}")

    async def list_nodes(self, label_selector: Optional[str] = None) -> List[dict]:
        params: Dict[str, str] = {}
        if label_selector:
            params["labelSelector"] = label_selector
        return await self._paged_list("/api/v1/nodes", params)

    async def patch_node(self, name: str, patch: dict) -> dict:
        return await self._request(
            "PATCH", f"/api/v1/nodes/{name}", body=patch,
            content_type="application/merge-patch+json",
        )

    async def replace_node(self, name: str, node: dict) -> dict:
        return await self._request("PUT", f"/api/v1/nodes/{name}", body=node)

    async def set_node_labels(self, name: str,
                              labels: Dict[str, Optional[str]]) -> dict:
        return await self.patch_node(name, {"metadata": {"labels": labels}})

    async def set_node_annotations(self, name: str,
                                   ann: Dict[str, Optional[str]]) -> dict:
        return await self.patch_node(name, {"metadata": {"annotations": ann}})

    async def _paged_list(self, path: str,
                          params: Dict[str, str]) -> List[dict]:
        items: List[dict] = []
        cont: Optional[str] = None
        while True:
            page = dict(params, limit=str(self.list_page_limit))
            if cont:
                page["continue"] = cont
            resp = await self._request(
                "GET", path + "?" + urllib.parse.urlencode(page)
            )
            items.extend(resp.get("items", []))
            cont = resp.get("metadata", {}).get("continue")
            if not cont:
                return items

    # -------------------------------------------------------------- pods
    async def list_pods(self, namespace: str,
                        label_selector: Optional[str] = None,
                        field_selector: Optional[str] = None) -> List[dict]:
        params: Dict[str, str] = {}
        if label_selector:
            params["labelSelector"] = label_selector
        if field_selector:
            params["fieldSelector"] = field_selector
        return await self._paged_list(
            f"/api/v1/namespaces/{namespace}/pods", params
        )

    async def delete_pod(self, namespace: str, name: str) -> None:
        await self._request(
            "DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}"
        )

    async def evict_pod(self, namespace: str, name: str) -> None:
        await self._request(
            "POST", f"/api/v1/namespaces/{namespace}/pods/{name}/eviction",
            body={
                "apiVersion": "policy/v1", "kind": "Eviction",
                "metadata": {"name": name, "namespace": namespace},
            },
        )

    # ---------------------------------------------------- events / leases
    async def create_event(self, namespace: str, event: dict) -> dict:
        return await self._request(
            "POST", f"/api/v1/namespaces/{namespace}/events", body=event
        )

    async def list_events(self, namespace: str) -> List[dict]:
        resp = await self._request(
            "GET", f"/api/v1/namespaces/{namespace}/events"
        )
        return resp.get("items", [])

    _LEASE_BASE = "/apis/coordination.k8s.io/v1/namespaces"

    async def get_lease(self, namespace: str, name: str) -> dict:
        return await self._request(
            "GET", f"{self._LEASE_BASE}/{namespace}/leases/{name}"
        )

    async def create_lease(self, namespace: str, lease: dict) -> dict:
        return await self._request(
            "POST", f"{self._LEASE_BASE}/{namespace}/leases", body=lease
        )

    async def replace_lease(self, namespace: str, name: str,
                            lease: dict) -> dict:
        return await self._request(
            "PUT", f"{self._LEASE_BASE}/{namespace}/leases/{name}",
            body=lease,
        )

    # --------------------------------------------------- custom resources
    async def list_cluster_custom(self, group: str, version: str,
                                  plural: str) -> List[dict]:
        return await self._paged_list(
            f"/apis/{group}/{version}/{plural}", {}
        )

    async def get_cluster_custom(self, group: str, version: str,
                                 plural: str, name: str) -> dict:
        return await self._request(
            "GET", f"/apis/{group}/{version}/{plural}/{name}"
        )

    async def patch_cluster_custom(self, group: str, version: str,
                                   plural: str, name: str, patch: dict,
                                   subresource: Optional[str] = None) -> dict:
        path = f"/apis/{group}/{version}/{plural}/{name}"
        if subresource:
            path += f"/{subresource}"
        return await self._request(
            "PATCH", path, body=patch,
            content_type="application/merge-patch+json",
        )

    # ------------------------------------------------------------- watch
    async def watch_nodes(self, name: Optional[str] = None,
                          resource_version: Optional[str] = None,
                          timeout_s: int = 300,
                          ) -> AsyncIterator[Tuple[str, dict]]:
        params = {
            "watch": "true",
            "timeoutSeconds": str(timeout_s),
            "allowWatchBookmarks": "true",
        }
        if name:
            params["fieldSelector"] = f"metadata.name={name}"
        if resource_version is not None:
            params["resourceVersion"] = str(resource_version)
        path = "/api/v1/nodes?" + urllib.parse.urlencode(params)
        async for item in self._stream_watch(path, timeout_s):
            yield item

    async def watch_cluster_custom(self, group: str, version: str,
                                   plural: str,
                                   resource_version: Optional[str] = None,
                                   timeout_s: int = 300,
                                   ) -> AsyncIterator[Tuple[str, dict]]:
        params = {"watch": "true", "timeoutSeconds": str(timeout_s)}
        if resource_version is not None:
            params["resourceVersion"] = str(resource_version)
        path = (f"/apis/{group}/{version}/{plural}?"
                + urllib.parse.urlencode(params))
        async for item in self._stream_watch(path, timeout_s):
            yield item

    async def _stream_watch(self, path: str, timeout_s: int,
                            ) -> AsyncIterator[Tuple[str, dict]]:
        """One watch = one DEDICATED connection (an unbounded chunked
        response cannot share a pipelined conn). Watch starts count
        against flow control like the sync client; the stream itself
        is free."""
        await self._throttle()
        self.watches_total += 1
        try:
            reader, writer = await self._dial()
        except OSError as e:
            raise ApiException(0, f"transport error: {e}") from e
        conn_alive = True
        try:
            writer.write(self._encode_request(
                "GET", path, None, "application/json",
                await self._auth_header(),
            ))
            await asyncio.wait_for(writer.drain(), DRAIN_TIMEOUT_S)
            line = await asyncio.wait_for(
                reader.readline(), timeout_s + 30
            )
            if not line:
                raise ApiException(0, "transport error: watch EOF before "
                                      "status line")
            status = int(line.split(None, 2)[1])
            headers: Dict[str, str] = {}
            while True:
                # a peer that wedges mid-header is as dead as one that
                # never sent the status line: same deadline
                hline = await asyncio.wait_for(
                    reader.readline(), timeout_s + 30
                )
                if not hline or hline in (b"\r\n", b"\n"):
                    break
                k, _, v = hline.decode("latin-1").partition(":")
                headers[k.strip().lower()] = v.strip()
            if status >= 400:
                body = await self._read_body(reader, headers)
                raise ApiException(
                    status, body.decode("utf-8", "replace")[:200]
                )
            buf = b""
            async for chunk in self._watch_payload(reader, headers,
                                                   timeout_s):
                buf += chunk
                while b"\n" in buf:
                    raw, buf = buf.split(b"\n", 1)
                    if not raw.strip():
                        continue
                    evt = json.loads(raw)
                    if evt.get("type") == "ERROR":
                        obj = evt.get("object", {})
                        raise ApiException(
                            int(obj.get("code", 500)),
                            obj.get("message", "watch error"),
                        )
                    yield evt["type"], evt["object"]
        except (OSError, asyncio.IncompleteReadError,
                asyncio.TimeoutError) as e:
            conn_alive = False
            raise ApiException(0, f"watch transport error: {e}") from e
        finally:
            try:
                writer.close()
                if conn_alive:
                    await writer.wait_closed()
            except Exception:  # ccaudit: allow-swallow(watch teardown: the socket may already be gone) # ccaudit: allow-async-exception(teardown in a finally after the transport error already re-raised; no futures pending on a dedicated watch conn)
                pass

    async def _watch_payload(self, reader: asyncio.StreamReader,
                             headers: Dict[str, str],
                             timeout_s: int) -> AsyncIterator[bytes]:
        """Chunked (the normal case) or raw-until-EOF payload stream,
        each read bounded so a wedged server can't hang the watcher
        past its own timeout window."""
        deadline = time.monotonic() + timeout_s + 30
        if headers.get("transfer-encoding", "").lower() == "chunked":
            it = self._iter_chunks(reader)
            while True:
                try:
                    chunk = await asyncio.wait_for(
                        it.__anext__(),
                        max(0.1, deadline - time.monotonic()),
                    )
                except StopAsyncIteration:
                    return
                yield chunk
        else:
            while True:
                chunk = await asyncio.wait_for(
                    reader.read(65536),
                    max(0.1, deadline - time.monotonic()),
                )
                if not chunk:
                    return
                yield chunk
