"""KubeClient interface + stdlib HTTP implementation.

Covers exactly the API surface the reference agents use (SURVEY.md §3.5):
node read/watch/patch, pod list/delete, eviction — nothing more. The HTTP
implementation speaks to a real API server (in-cluster service account or
kubeconfig) or to :mod:`tpu_cc_manager.k8s.apiserver` in tests.
"""

from __future__ import annotations

import abc
import base64
import json
import os
import socket
import ssl
import tempfile
import urllib.parse
from http.client import HTTPConnection, HTTPSConnection
from typing import Dict, Iterator, List, Optional, Tuple


class ApiException(Exception):
    """HTTP-level API failure (status carries the k8s semantics: 404 absent,
    409 conflict, 410 watch-history expired, 429 PDB-blocked eviction)."""

    def __init__(self, status: int, reason: str = ""):
        super().__init__(f"k8s API error {status}: {reason}")
        self.status = status
        self.reason = reason


class ConflictError(ApiException):
    def __init__(self, reason: str = "resourceVersion conflict"):
        super().__init__(409, reason)


class KubeClient(abc.ABC):
    """The minimal clientset both agents are written against."""

    @abc.abstractmethod
    def get_node(self, name: str) -> dict: ...

    @abc.abstractmethod
    def list_nodes(self, label_selector: Optional[str] = None) -> List[dict]: ...

    @abc.abstractmethod
    def patch_node(self, name: str, patch: dict) -> dict:
        """JSON merge patch (labels/annotations/spec)."""

    @abc.abstractmethod
    def replace_node(self, name: str, node: dict) -> dict:
        """Optimistic-concurrency replace: raises ConflictError when
        node['metadata']['resourceVersion'] is stale. Used for slice
        leader election CAS."""

    @abc.abstractmethod
    def list_pods(
        self,
        namespace: str,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
    ) -> List[dict]: ...

    @abc.abstractmethod
    def delete_pod(self, namespace: str, name: str) -> None: ...

    @abc.abstractmethod
    def evict_pod(self, namespace: str, name: str) -> None:
        """Eviction API (respects PDBs -> ApiException(429) when blocked)."""

    @abc.abstractmethod
    def watch_nodes(
        self,
        name: Optional[str] = None,
        resource_version: Optional[str] = None,
        timeout_s: int = 300,
    ) -> Iterator[Tuple[str, dict]]:
        """Yield (event_type, node) until server-side timeout. Raises
        ApiException(410) when resource_version fell out of history —
        callers re-list and resume (reference main.py:675-687)."""

    # convenience built on the primitives -------------------------------
    def set_node_labels(self, name: str, labels: Dict[str, Optional[str]]) -> dict:
        return self.patch_node(name, {"metadata": {"labels": labels}})

    def set_node_annotations(self, name: str, ann: Dict[str, Optional[str]]) -> dict:
        return self.patch_node(name, {"metadata": {"annotations": ann}})


# --------------------------------------------------------------------------
# configuration loading
# --------------------------------------------------------------------------

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeConfig:
    def __init__(
        self,
        host: str,
        port: int,
        *,
        use_tls: bool = True,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        client_cert: Optional[str] = None,
        client_key: Optional[str] = None,
        insecure_skip_verify: bool = False,
    ):
        self.host = host
        self.port = port
        self.use_tls = use_tls
        self.token = token
        self.ca_file = ca_file
        self.client_cert = client_cert
        self.client_key = client_key
        self.insecure_skip_verify = insecure_skip_verify

    @classmethod
    def in_cluster(cls) -> "KubeConfig":
        """Service-account config, the DaemonSet path (reference
        main.py:105-110 uses load_incluster_config)."""
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = int(os.environ.get("KUBERNETES_SERVICE_PORT", "443"))
        token_path = os.path.join(SERVICE_ACCOUNT_DIR, "token")
        ca_path = os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
        with open(token_path) as f:
            token = f.read().strip()
        return cls(host, port, token=token,
                   ca_file=ca_path if os.path.exists(ca_path) else None)

    @classmethod
    def from_kubeconfig(cls, path: str) -> "KubeConfig":
        """Parse a kubeconfig file (reference main.py:111-114 falls back to
        load_kube_config when not in-cluster)."""
        import yaml

        with open(path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = cfg.get("current-context")
        ctx = next(c for c in cfg["contexts"] if c["name"] == ctx_name)["context"]
        cluster = next(
            c for c in cfg["clusters"] if c["name"] == ctx["cluster"]
        )["cluster"]
        user = next(u for u in cfg["users"] if u["name"] == ctx["user"])["user"]

        url = urllib.parse.urlparse(cluster["server"])
        use_tls = url.scheme == "https"
        port = url.port or (443 if use_tls else 80)

        def _inline(data_key: str, file_key: str, blob: dict) -> Optional[str]:
            if blob.get(file_key):
                return blob[file_key]
            if blob.get(data_key):
                fd, p = tempfile.mkstemp(prefix="kubecfg-")
                with os.fdopen(fd, "wb") as f:
                    f.write(base64.b64decode(blob[data_key]))
                return p
            return None

        return cls(
            url.hostname or "localhost",
            port,
            use_tls=use_tls,
            token=user.get("token"),
            ca_file=_inline("certificate-authority-data", "certificate-authority", cluster),
            client_cert=_inline("client-certificate-data", "client-certificate", user),
            client_key=_inline("client-key-data", "client-key", user),
            insecure_skip_verify=bool(cluster.get("insecure-skip-tls-verify")),
        )

    @classmethod
    def load(cls, kubeconfig: Optional[str] = None) -> "KubeConfig":
        """In-cluster first, kubeconfig fallback — the same resolution
        order as the reference (main.py:105-114)."""
        if kubeconfig:
            return cls.from_kubeconfig(kubeconfig)
        if "KUBERNETES_SERVICE_HOST" in os.environ:
            return cls.in_cluster()
        default = os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config")
        )
        return cls.from_kubeconfig(default)


# --------------------------------------------------------------------------
# HTTP implementation
# --------------------------------------------------------------------------


class HttpKubeClient(KubeClient):
    def __init__(self, config: KubeConfig):
        self.config = config

    # -- plumbing -------------------------------------------------------
    def _connect(self, read_timeout: Optional[float]) -> HTTPConnection:
        c = self.config
        if c.use_tls:
            ctx = ssl.create_default_context(cafile=c.ca_file)
            if c.insecure_skip_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if c.client_cert:
                ctx.load_cert_chain(c.client_cert, c.client_key)
            return HTTPSConnection(c.host, c.port, context=ctx, timeout=read_timeout)
        return HTTPConnection(c.host, c.port, timeout=read_timeout)

    def _headers(self, content_type: Optional[str] = None) -> dict:
        h = {"Accept": "application/json"}
        if self.config.token:
            h["Authorization"] = f"Bearer {self.config.token}"
        if content_type:
            h["Content-Type"] = content_type
        return h

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        content_type: str = "application/json",
        read_timeout: Optional[float] = 30.0,
    ) -> dict:
        conn = self._connect(read_timeout)
        try:
            try:
                conn.request(
                    method,
                    path,
                    body=json.dumps(body) if body is not None else None,
                    headers=self._headers(content_type if body is not None else None),
                )
                resp = conn.getresponse()
                data = resp.read()
            except OSError as e:
                # transport failure (refused/reset/timeout): surface as an
                # API error (status 0) so callers' retry/backoff paths —
                # not a raw traceback — handle it
                raise ApiException(0, f"transport error: {e}") from e
            if resp.status >= 400:
                if resp.status == 409:
                    raise ConflictError(data.decode("utf-8", "replace")[:200])
                raise ApiException(resp.status, data.decode("utf-8", "replace")[:200])
            return json.loads(data) if data else {}
        finally:
            conn.close()

    # -- nodes ----------------------------------------------------------
    def get_node(self, name: str) -> dict:
        return self._request("GET", f"/api/v1/nodes/{name}")

    def list_nodes(self, label_selector: Optional[str] = None) -> List[dict]:
        q = ""
        if label_selector:
            q = "?labelSelector=" + urllib.parse.quote(label_selector)
        return self._request("GET", f"/api/v1/nodes{q}").get("items", [])

    def patch_node(self, name: str, patch: dict) -> dict:
        return self._request(
            "PATCH",
            f"/api/v1/nodes/{name}",
            body=patch,
            content_type="application/merge-patch+json",
        )

    def replace_node(self, name: str, node: dict) -> dict:
        return self._request("PUT", f"/api/v1/nodes/{name}", body=node)

    # -- pods -----------------------------------------------------------
    def list_pods(
        self,
        namespace: str,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
    ) -> List[dict]:
        params = {}
        if label_selector:
            params["labelSelector"] = label_selector
        if field_selector:
            params["fieldSelector"] = field_selector
        q = ("?" + urllib.parse.urlencode(params)) if params else ""
        return self._request(
            "GET", f"/api/v1/namespaces/{namespace}/pods{q}"
        ).get("items", [])

    def delete_pod(self, namespace: str, name: str) -> None:
        self._request("DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def evict_pod(self, namespace: str, name: str) -> None:
        self._request(
            "POST",
            f"/api/v1/namespaces/{namespace}/pods/{name}/eviction",
            body={
                "apiVersion": "policy/v1",
                "kind": "Eviction",
                "metadata": {"name": name, "namespace": namespace},
            },
        )

    # -- watch ----------------------------------------------------------
    def watch_nodes(
        self,
        name: Optional[str] = None,
        resource_version: Optional[str] = None,
        timeout_s: int = 300,
    ) -> Iterator[Tuple[str, dict]]:
        params = {"watch": "true", "timeoutSeconds": str(timeout_s)}
        if name:
            # node-scoped watch, exactly like the Go informer's fieldSelector
            # metadata.name=<node> (reference cmd/main.go:185-190)
            params["fieldSelector"] = f"metadata.name={name}"
        if resource_version is not None:
            params["resourceVersion"] = str(resource_version)
        path = "/api/v1/nodes?" + urllib.parse.urlencode(params)

        conn = self._connect(read_timeout=timeout_s + 30)
        try:
            try:
                conn.request("GET", path, headers=self._headers())
                resp = conn.getresponse()
            except OSError as e:
                raise ApiException(0, f"transport error: {e}") from e
            if resp.status >= 400:
                raise ApiException(resp.status, resp.read().decode("utf-8", "replace")[:200])
            # newline-delimited JSON event stream
            buf = b""
            while True:
                try:
                    chunk = resp.read1(65536)
                except (socket.timeout, ssl.SSLError) as e:
                    raise ApiException(0, f"watch read timeout: {e}")
                if not chunk:
                    return  # server closed (watch timeout elapsed)
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    evt = json.loads(line)
                    if evt.get("type") == "ERROR":
                        status = evt.get("object", {})
                        raise ApiException(
                            int(status.get("code", 500)),
                            status.get("message", "watch error"),
                        )
                    yield evt["type"], evt["object"]
        finally:
            conn.close()
