"""KubeClient interface + stdlib HTTP implementation.

Covers exactly the API surface the reference agents use (SURVEY.md §3.5):
node read/watch/patch, pod list/delete, eviction — nothing more. The HTTP
implementation speaks to a real API server (in-cluster service account or
kubeconfig) or to :mod:`tpu_cc_manager.k8s.apiserver` in tests.
"""

from __future__ import annotations

import abc
import base64
import datetime
import json
import logging
import os
import socket
import ssl
import subprocess
import tempfile
import threading
import time
import urllib.parse
from http.client import (
    BadStatusLine,
    HTTPConnection,
    HTTPException,
    HTTPSConnection,
)
from typing import Dict, Iterator, List, Optional, Tuple

log = logging.getLogger("tpu-cc-manager.k8s")


class ApiException(Exception):
    """HTTP-level API failure (status carries the k8s semantics: 404 absent,
    409 conflict, 410 watch-history expired, 429 PDB-blocked eviction)."""

    def __init__(self, status: int, reason: str = ""):
        super().__init__(f"k8s API error {status}: {reason}")
        self.status = status
        self.reason = reason


class ConflictError(ApiException):
    def __init__(self, reason: str = "resourceVersion conflict"):
        super().__init__(409, reason)


class KubeClient(abc.ABC):
    """The minimal clientset both agents are written against."""

    @abc.abstractmethod
    def get_node(self, name: str) -> dict: ...

    @abc.abstractmethod
    def list_nodes(self, label_selector: Optional[str] = None) -> List[dict]: ...

    @abc.abstractmethod
    def patch_node(self, name: str, patch: dict) -> dict:
        """JSON merge patch (labels/annotations/spec)."""

    @abc.abstractmethod
    def replace_node(self, name: str, node: dict) -> dict:
        """Optimistic-concurrency replace: raises ConflictError when
        node['metadata']['resourceVersion'] is stale. Used for slice
        leader election CAS."""

    @abc.abstractmethod
    def list_pods(
        self,
        namespace: str,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
    ) -> List[dict]: ...

    @abc.abstractmethod
    def delete_pod(self, namespace: str, name: str) -> None: ...

    @abc.abstractmethod
    def evict_pod(self, namespace: str, name: str) -> None:
        """Eviction API (respects PDBs -> ApiException(429) when blocked)."""

    @abc.abstractmethod
    def watch_nodes(
        self,
        name: Optional[str] = None,
        resource_version: Optional[str] = None,
        timeout_s: int = 300,
    ) -> Iterator[Tuple[str, dict]]:
        """Yield (event_type, node) until server-side timeout. Raises
        ApiException(410) when resource_version fell out of history —
        callers re-list and resume (reference main.py:675-687)."""

    def create_event(self, namespace: str, event: dict) -> dict:
        """Create a core/v1 Event (observability only — reconcile
        outcomes surface in ``kubectl describe node``). Non-abstract so
        minimal clientsets/test doubles keep working; callers treat
        emission as best-effort."""
        raise ApiException(501, "events not supported by this client")

    def list_events(self, namespace: str) -> List[dict]:
        """List the namespace's core/v1 Events (kubectl-describe analog
        for smokes/tests)."""
        raise ApiException(501, "events not supported by this client")

    # -- cluster-scoped custom resources (CRDs) -------------------------
    # Non-abstract with a 501 default, like the events surface: only the
    # policy controller needs CRs, and minimal clientsets/test doubles
    # must keep working unchanged.
    def list_cluster_custom(
        self, group: str, version: str, plural: str
    ) -> List[dict]:
        """List a cluster-scoped custom resource collection
        (``GET /apis/{group}/{version}/{plural}``)."""
        raise ApiException(501, "custom resources not supported by this client")

    def get_cluster_custom(
        self, group: str, version: str, plural: str, name: str
    ) -> dict:
        raise ApiException(501, "custom resources not supported by this client")

    def patch_cluster_custom(
        self,
        group: str,
        version: str,
        plural: str,
        name: str,
        patch: dict,
        subresource: Optional[str] = None,
    ) -> dict:
        """JSON merge patch on a cluster-scoped custom resource;
        ``subresource="status"`` patches the status subresource (which,
        like the real API server, never bumps ``metadata.generation``)."""
        raise ApiException(501, "custom resources not supported by this client")

    def watch_cluster_custom(
        self,
        group: str,
        version: str,
        plural: str,
        resource_version: Optional[str] = None,
        timeout_s: int = 300,
    ) -> Iterator[Tuple[str, dict]]:
        """Watch a cluster-scoped CR collection; yields (event_type,
        object) until the server-side timeout, like watch_nodes."""
        raise ApiException(501, "custom resources not supported by this client")

    # leases (coordination.k8s.io/v1) -----------------------------------
    # the leader-election primitive (tpu_cc_manager.leader): namespaced
    # Lease objects with optimistic-concurrency replace — exactly the
    # trio client-go's resourcelock.LeaseLock uses
    def get_lease(self, namespace: str, name: str) -> dict:
        raise ApiException(501, "leases not supported by this client")

    def create_lease(self, namespace: str, lease: dict) -> dict:
        """POST; raises ApiException(409) if it already exists."""
        raise ApiException(501, "leases not supported by this client")

    def replace_lease(self, namespace: str, name: str,
                      lease: dict) -> dict:
        """PUT with the object's metadata.resourceVersion; raises
        ConflictError when the server's moved on (someone else renewed
        or took the lease first — the CAS that makes election safe)."""
        raise ApiException(501, "leases not supported by this client")

    # convenience built on the primitives -------------------------------
    def set_node_labels(self, name: str, labels: Dict[str, Optional[str]]) -> dict:
        return self.patch_node(name, {"metadata": {"labels": labels}})

    def set_node_annotations(self, name: str, ann: Dict[str, Optional[str]]) -> dict:
        return self.patch_node(name, {"metadata": {"annotations": ann}})


# --------------------------------------------------------------------------
# configuration loading
# --------------------------------------------------------------------------

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ExecCredentialError(Exception):
    """A kubeconfig users[].exec credential plugin failed or returned an
    unusable ExecCredential."""


class ExecCredentialPlugin:
    """Runs a kubeconfig ``users[].exec`` credential plugin and caches the
    resulting bearer token until its ``expirationTimestamp``.

    This is GKE's actual auth path: real GKE kubeconfigs carry no static
    token — they name ``gke-gcloud-auth-plugin``, which prints an
    ExecCredential JSON on stdout. The reference gets this for free from
    client-go (reference cmd/main.go:120, clientcmd.BuildConfigFromFlags)
    and the kubernetes Python client (reference main.py:105-114); this is
    the stdlib equivalent for the operator-side tools (rollout,
    fleet-controller, plan) running from a workstation.

    Implements the client-go contract:
    - spawn ``command args...`` with ``env`` entries merged over os.environ;
    - when ``provideClusterInfo`` is set, pass the target cluster through
      the ``KUBERNETES_EXEC_INFO`` env var;
    - parse the ExecCredential status: ``token`` (primary; GKE) and the
      ``clientCertificateData``/``clientKeyData`` pair (some plugins);
    - cache until ``expirationTimestamp`` minus a refresh skew; a
      credential with no expiry is cached for the process lifetime.
    """

    REFRESH_SKEW_S = 60

    def __init__(self, spec: dict, cluster: Optional[dict] = None):
        self.command = spec["command"]
        self.args = list(spec.get("args") or [])
        self.env = list(spec.get("env") or [])  # [{"name":..., "value":...}]
        self.api_version = spec.get(
            "apiVersion", "client.authentication.k8s.io/v1beta1"
        )
        self.provide_cluster_info = bool(spec.get("provideClusterInfo"))
        self.cluster = cluster or {}
        self.timeout_s = 60.0
        self._lock = threading.Lock()
        self._token: Optional[str] = None
        self._cert_files: Optional[Tuple[str, str]] = None
        self._expiry: Optional[datetime.datetime] = None
        self._fetched = False

    # -- cache ----------------------------------------------------------
    def _fresh(self, now: datetime.datetime) -> bool:
        if not self._fetched:
            return False
        if self._expiry is None:
            return True  # no expiry: valid for process lifetime (client-go)
        return now < self._expiry - datetime.timedelta(seconds=self.REFRESH_SKEW_S)

    def token(self) -> Optional[str]:
        with self._lock:
            # ccaudit: allow-blocking-under-lock(single-flight credential fetch: the lock exists so N threads with an expired token exec the plugin once, not N times)
            self._ensure(datetime.datetime.now(datetime.timezone.utc))
            return self._token

    def client_cert_pair(self) -> Optional[Tuple[str, str]]:
        """(cert_file, key_file) when the plugin returned TLS credentials."""
        with self._lock:
            # ccaudit: allow-blocking-under-lock(single-flight credential fetch, same contract as token() above)
            self._ensure(datetime.datetime.now(datetime.timezone.utc))
            return self._cert_files

    def invalidate(self) -> None:
        """Drop the cached credential (e.g. after a 401) so the next
        request re-runs the plugin."""
        with self._lock:
            self._fetched = False

    # -- plugin invocation ----------------------------------------------
    def _ensure(self, now: datetime.datetime) -> None:
        if self._fresh(now):
            return
        status = self._invoke()
        self._token = status.get("token")
        self._expiry = _parse_rfc3339(status.get("expirationTimestamp"))
        cert, key = status.get("clientCertificateData"), status.get("clientKeyData")
        if cert and key:
            # reuse the same two paths across refreshes: a short-expiry
            # plugin in a long-running controller must not grow /tmp (and
            # must not leave a trail of stale private keys). Swap contents
            # atomically — another thread may be load_cert_chain()ing the
            # previous credential off these paths right now.
            if self._cert_files is None:
                self._cert_files = (_write_temp(b""), _write_temp(b""))
            for path, data in zip(self._cert_files, (cert, key)):
                os.replace(_write_temp(data.encode()), path)
        elif self._cert_files is not None:
            for path in self._cert_files:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._cert_files = None
        if not self._token and not self._cert_files:
            raise ExecCredentialError(
                f"{self.command}: ExecCredential carries neither token nor "
                "client certificate"
            )
        self._fetched = True

    def _invoke(self) -> dict:
        env = dict(os.environ)
        for e in self.env:
            env[e["name"]] = e["value"]
        if self.provide_cluster_info:
            # client-go ExecCredential input contract (KUBERNETES_EXEC_INFO)
            env["KUBERNETES_EXEC_INFO"] = json.dumps({
                "apiVersion": self.api_version,
                "kind": "ExecCredential",
                "spec": {
                    "interactive": False,
                    "cluster": {
                        "server": self.cluster.get("server", ""),
                        "certificate-authority-data":
                            self.cluster.get("certificate-authority-data", ""),
                    },
                },
            })
        try:
            proc = subprocess.run(
                [self.command, *self.args],
                env=env,
                capture_output=True,
                text=True,
                timeout=self.timeout_s,
            )
        except FileNotFoundError:
            raise ExecCredentialError(
                f"credential plugin not found: {self.command}"
            ) from None
        except subprocess.TimeoutExpired:
            raise ExecCredentialError(
                f"credential plugin timed out after {self.timeout_s}s: "
                f"{self.command}"
            ) from None
        if proc.returncode != 0:
            raise ExecCredentialError(
                f"credential plugin failed (rc={proc.returncode}): "
                f"{self.command}: {proc.stderr.strip()[:200]}"
            )
        try:
            cred = json.loads(proc.stdout)
        except json.JSONDecodeError as e:
            raise ExecCredentialError(
                f"credential plugin printed invalid JSON: {self.command}: {e}"
            ) from None
        if cred.get("kind") not in (None, "ExecCredential"):
            raise ExecCredentialError(
                f"credential plugin returned kind={cred.get('kind')!r}, "
                "expected ExecCredential"
            )
        return cred.get("status") or {}


def _parse_rfc3339(ts: Optional[str]) -> Optional[datetime.datetime]:
    if not ts:
        return None
    try:
        dt = datetime.datetime.fromisoformat(ts.replace("Z", "+00:00"))
    except ValueError:
        return None  # unparseable expiry: treat as non-expiring
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt


def _write_temp(data: bytes, prefix: str = "kubecfg-") -> str:
    fd, p = tempfile.mkstemp(prefix=prefix)
    with os.fdopen(fd, "wb") as f:
        f.write(data)
    return p


class KubeConfig:
    def __init__(
        self,
        host: str,
        port: int,
        *,
        use_tls: bool = True,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        client_cert: Optional[str] = None,
        client_key: Optional[str] = None,
        insecure_skip_verify: bool = False,
        exec_plugin: Optional[ExecCredentialPlugin] = None,
    ):
        self.host = host
        self.port = port
        self.use_tls = use_tls
        self.token = token
        self.ca_file = ca_file
        self.client_cert = client_cert
        self.client_key = client_key
        self.insecure_skip_verify = insecure_skip_verify
        self.exec_plugin = exec_plugin

    def bearer_token(self) -> Optional[str]:
        """The token for the next request: static when the kubeconfig
        carries one, otherwise freshly resolved (and cached) through the
        exec credential plugin."""
        if self.token:
            return self.token
        if self.exec_plugin:
            return self.exec_plugin.token()
        return None

    def client_cert_pair(self) -> Optional[Tuple[str, str]]:
        if self.client_cert:
            return (self.client_cert, self.client_key)
        if self.exec_plugin:
            return self.exec_plugin.client_cert_pair()
        return None

    @classmethod
    def in_cluster(cls) -> "KubeConfig":
        """Service-account config, the DaemonSet path (reference
        main.py:105-110 uses load_incluster_config)."""
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = int(os.environ.get("KUBERNETES_SERVICE_PORT", "443"))
        token_path = os.path.join(SERVICE_ACCOUNT_DIR, "token")
        ca_path = os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
        with open(token_path) as f:
            token = f.read().strip()
        return cls(host, port, token=token,
                   ca_file=ca_path if os.path.exists(ca_path) else None)

    @classmethod
    def from_kubeconfig(cls, path: str, context: Optional[str] = None) -> "KubeConfig":
        """Parse a kubeconfig file (reference main.py:111-114 falls back to
        load_kube_config when not in-cluster). Supports static tokens,
        inline/file client certificates, and ``users[].exec`` credential
        plugins — the gke-gcloud-auth-plugin path every real GKE
        kubeconfig uses."""
        import yaml

        with open(path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = context or cfg.get("current-context")
        try:
            ctx = next(c for c in cfg["contexts"] if c["name"] == ctx_name)["context"]
        except StopIteration:
            raise ValueError(f"kubeconfig {path}: context {ctx_name!r} not found") from None
        try:
            cluster = next(
                c for c in cfg["clusters"] if c["name"] == ctx["cluster"]
            )["cluster"]
        except StopIteration:
            raise ValueError(f"kubeconfig {path}: cluster {ctx['cluster']!r} not found") from None
        try:
            user = next(u for u in cfg["users"] if u["name"] == ctx["user"])["user"]
        except StopIteration:
            raise ValueError(f"kubeconfig {path}: user {ctx['user']!r} not found") from None

        url = urllib.parse.urlparse(cluster["server"])
        use_tls = url.scheme == "https"
        port = url.port or (443 if use_tls else 80)

        def _inline(data_key: str, file_key: str, blob: dict) -> Optional[str]:
            if blob.get(file_key):
                return blob[file_key]
            if blob.get(data_key):
                return _write_temp(base64.b64decode(blob[data_key]))
            return None

        exec_plugin = None
        if user.get("exec"):
            exec_plugin = ExecCredentialPlugin(user["exec"], cluster=cluster)

        return cls(
            url.hostname or "localhost",
            port,
            use_tls=use_tls,
            token=user.get("token"),
            ca_file=_inline("certificate-authority-data", "certificate-authority", cluster),
            client_cert=_inline("client-certificate-data", "client-certificate", user),
            client_key=_inline("client-key-data", "client-key", user),
            insecure_skip_verify=bool(cluster.get("insecure-skip-tls-verify")),
            exec_plugin=exec_plugin,
        )

    @classmethod
    def load(cls, kubeconfig: Optional[str] = None) -> "KubeConfig":
        """In-cluster first, kubeconfig fallback — the same resolution
        order as the reference (main.py:105-114)."""
        if kubeconfig:
            return cls.from_kubeconfig(kubeconfig)
        if "KUBERNETES_SERVICE_HOST" in os.environ:
            return cls.in_cluster()
        default = os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config")
        )
        return cls.from_kubeconfig(default)


# --------------------------------------------------------------------------
# HTTP implementation
# --------------------------------------------------------------------------


class _TokenBucket:
    """client-go-style client-side flow control (QPS + burst,
    vendor/k8s.io/client-go rest.Config's QPS/Burst): a shared bucket
    refilled at ``qps`` tokens/second, holding at most ``burst``.
    ``acquire`` blocks until a token is available — requests are
    delayed, never dropped, so a controller storm degrades to a steady
    trickle instead of hammering a contended API server. Thread-safe;
    one bucket serves every thread of a client instance."""

    def __init__(self, qps: float, burst: int):
        self.qps = float(qps)
        self.burst = max(1, int(burst))
        self._tokens = float(self.burst)
        self._updated = time.monotonic()
        self._lock = threading.Lock()

    def acquire(self) -> float:
        """Take one token, blocking as needed; returns the seconds this
        caller spent waiting — the number that turns "is the limiter
        actually throttling us?" from a guess into a metric."""
        waited = 0.0
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(
                    float(self.burst),
                    self._tokens + (now - self._updated) * self.qps,
                )
                self._updated = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return waited
                wait = (1.0 - self._tokens) / self.qps
            time.sleep(wait)
            waited += wait


class HttpKubeClient(KubeClient):
    #: items per page for list requests; the server may return fewer and a
    #: ``metadata.continue`` token, which list_nodes/list_pods follow —
    #: required at fleet scale (client-go informers paginate the same way)
    LIST_PAGE_LIMIT = 500

    #: default ceiling on pooled idle keep-alive connections
    #: (``TPU_CC_KUBE_CONNS`` overrides): enough to overlap the flip
    #: executor's default worker count plus the agent's recorder/watch
    #: side traffic without hoarding sockets per client instance
    POOL_MAXSIZE = 8

    def __init__(self, config: KubeConfig,
                 list_page_limit: Optional[int] = None,
                 qps: Optional[float] = None,
                 burst: Optional[int] = None,
                 pool_maxsize: Optional[int] = None):
        self.config = config
        self.list_page_limit = list_page_limit or self.LIST_PAGE_LIMIT
        # a small SHARED pool of persistent keep-alive connections: the
        # historical one-connection-per-thread (threading.local) model
        # meant every short-lived thread — the flip executor's workers,
        # per-reconcile helpers — dialed TCP(+TLS) fresh and leaked the
        # socket when the thread died. The shared pool survives thread
        # churn: any thread checks a connection out for one request and
        # returns it, so TPU_CC_FLIP_CONCURRENCY workers reuse the same
        # few warm sockets instead of serializing on connection setup
        # (r1 VERDICT weak #3; ISSUE 6 flip-path I/O)
        if pool_maxsize is None:
            try:
                pool_maxsize = int(
                    os.environ.get("TPU_CC_KUBE_CONNS", "") or 0
                ) or None
            except ValueError:
                pool_maxsize = None
        self.pool_maxsize = pool_maxsize or self.POOL_MAXSIZE
        self._conns: List[HTTPConnection] = []  # idle, LIFO (warmest last)
        self._conn_lock = threading.Lock()
        self._pool_closed = False  # close() stops re-pooling at release
        # client-side flow control (TPU_CC_KUBE_QPS / TPU_CC_KUBE_BURST,
        # ctor args win): OFF by default — a per-node agent makes a
        # handful of writes per reconcile and must not trade flip
        # latency for politeness. The shipped controller manifests set
        # a QPS: one fleet/policy controller scanning thousands of
        # nodes is where client-go reaches for rest.Config.QPS/Burst,
        # and the reference's ecosystem gets that limiter for free
        # (vendor/k8s.io/client-go in the reference tree)
        if qps is None:
            try:
                qps = float(os.environ.get("TPU_CC_KUBE_QPS", "") or 0)
            except ValueError:
                qps = 0
        self._bucket: Optional[_TokenBucket] = None
        if qps and qps > 0:
            if burst is None:
                try:
                    burst = int(
                        os.environ.get("TPU_CC_KUBE_BURST", "") or 0
                    ) or None
                except ValueError:
                    burst = None
            # client-go's default Burst is 2x QPS-ish (5/10); same ratio
            self._bucket = _TokenBucket(qps, burst or int(2 * qps))
        # throttle visibility (client-go's
        # rest_client_rate_limiter_duration_seconds analog): plain
        # best-effort totals here (metrics, not bookkeeping — an
        # unsynchronized += across threads can at worst lose a sample),
        # plus an optional observer the owning controller wires to its
        # own Histogram so /metrics carries the distribution.
        self.throttle_waits = 0
        self.throttle_wait_s_total = 0.0
        self._throttle_observers: list = []

    def add_throttle_observer(self, fn) -> None:
        """Wire a callable(seconds) observed on EVERY flow-controlled
        request (zero when no wait): the controllers pass their
        ``tpu_cc_kube_throttle_wait_seconds`` histogram's observe. A
        LIST, not a slot — two controllers sharing one client must
        both see the waits, not whoever registered last."""
        self._throttle_observers.append(fn)

    def set_qps(self, qps: float, burst: Optional[int] = None) -> None:
        """Retune client-side flow control at runtime (simlab's
        throttle-squeeze fault; ops tooling reacting to API-server
        pressure). ``qps <= 0`` removes the limiter. In-flight waiters
        finish against the bucket they started on — only new requests
        see the new rate."""
        if qps and qps > 0:
            self._bucket = _TokenBucket(qps, burst or int(2 * qps))
        else:
            self._bucket = None

    def _acquire_token(self) -> None:
        bucket = self._bucket  # one read: set_qps may swap it mid-call
        if bucket is None:
            return
        waited = bucket.acquire()
        if waited > 0:
            self.throttle_waits += 1
            self.throttle_wait_s_total += waited
        for fn in self._throttle_observers:
            try:
                fn(waited)
            except Exception:
                # observability must never sink a request
                log.debug("throttle observer failed", exc_info=True)

    # -- plumbing -------------------------------------------------------
    def _acquire_conn(
        self, read_timeout: Optional[float]
    ) -> Tuple[HTTPConnection, bool]:
        """Check a connection out of the shared pool — (connection,
        is_fresh). A checked-out connection is owned by the calling
        thread until ``_release_conn``/``_discard_conn``; dead pooled
        sockets are dropped and replaced by a fresh dial."""
        while True:
            with self._conn_lock:
                conn = self._conns.pop() if self._conns else None
            if conn is None:
                return self._connect(read_timeout), True
            if conn.sock is None:
                # server sent Connection: close on its previous response
                conn.close()
                continue
            if read_timeout is not None:
                try:
                    conn.sock.settimeout(read_timeout)
                except OSError:
                    # socket died while idle: drop, try the next one
                    self._discard_conn(conn)
                    continue
            return conn, False

    def _release_conn(self, conn: HTTPConnection) -> None:
        """Return a healthy connection to the pool (or close it when the
        pool is full, the client was close()d, or the server asked to
        close)."""
        if conn.sock is None:
            conn.close()
            return
        with self._conn_lock:
            if not self._pool_closed and len(self._conns) < self.pool_maxsize:
                self._conns.append(conn)
                return
        conn.close()

    def _discard_conn(self, conn: Optional[HTTPConnection]) -> None:
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        """Close every idle pooled connection and stop accepting
        returns: a connection in flight during close() is closed by its
        owning thread at release instead of being re-pooled. The client
        remains usable (new requests dial fresh) — close() reclaims
        sockets, it does not poison the instance."""
        with self._conn_lock:
            conns, self._conns = self._conns, []
            self._pool_closed = True
        for conn in conns:
            self._discard_conn(conn)

    def _connect(self, read_timeout: Optional[float]) -> HTTPConnection:
        c = self.config
        if c.use_tls:
            ctx = ssl.create_default_context(cafile=c.ca_file)
            if c.insecure_skip_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            pair = c.client_cert_pair()
            if pair:
                ctx.load_cert_chain(pair[0], pair[1])
            return HTTPSConnection(c.host, c.port, context=ctx, timeout=read_timeout)
        return HTTPConnection(c.host, c.port, timeout=read_timeout)

    def _headers(self, content_type: Optional[str] = None) -> dict:
        h = {"Accept": "application/json"}
        token = self.config.bearer_token()
        if token:
            h["Authorization"] = f"Bearer {token}"
        if content_type:
            h["Content-Type"] = content_type
        return h

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        content_type: str = "application/json",
        read_timeout: Optional[float] = 30.0,
        _auth_retry: bool = True,
    ) -> dict:
        self._acquire_token()
        resp = data = None
        conn: Optional[HTTPConnection] = None
        for attempt in (0, 1):
            try:
                if attempt == 0:
                    conn, fresh = self._acquire_conn(read_timeout)
                else:
                    # replay attempt: dial FRESH, bypassing the pool —
                    # after a server restart several idle pooled conns
                    # may all be stale, and popping another one here
                    # would turn a replayable race into a terminal
                    # error (the one-conn-per-thread model always
                    # replayed on a fresh dial; keep that guarantee)
                    conn, fresh = self._connect(read_timeout), True
            except ExecCredentialError as e:
                # surface credential-plugin failures through the module's
                # error contract so callers' except-ApiException
                # retry/rollback paths (rollout, agent watch loop) handle
                # them like any transport failure instead of crashing on a
                # foreign exception type
                raise ApiException(0, f"exec credential failure: {e}") from e
            try:
                conn.request(
                    method,
                    path,
                    body=json.dumps(body) if body is not None else None,
                    headers=self._headers(content_type if body is not None else None),
                )
                resp = conn.getresponse()
                data = resp.read()  # drain fully so the conn is reusable
                break
            except ExecCredentialError as e:
                self._discard_conn(conn)
                raise ApiException(0, f"exec credential failure: {e}") from e
            except (OSError, HTTPException) as e:
                # Replay ONLY the stale keep-alive race: a reused
                # connection the server closed before sending any response
                # bytes (RemoteDisconnected/BadStatusLine — Go's net/http
                # retries exactly this on reused connections). Anything
                # else — a timeout or reset mid-response, any failure on a
                # fresh connection — may have already executed server-side,
                # so replaying a non-idempotent PATCH/DELETE would double-
                # apply it; surface as an API error (status 0) and let the
                # caller's retry/backoff own the decision. EXACTLY-ONCE
                # under the shared pool: the replay dials fresh (never
                # another possibly-stale pooled conn), and a failure on
                # that fresh dial is terminal (not replayable).
                self._discard_conn(conn)
                replayable = isinstance(e, BadStatusLine) and not fresh
                if not replayable or attempt == 1:
                    raise ApiException(0, f"transport error: {e}") from e
        if resp.status == 401 and _auth_retry and self.config.exec_plugin:
            # cached exec credential revoked server-side: refresh once
            # (client-go invalidate-and-retry contract). Drop this
            # connection too — a refreshed exec client *certificate* only
            # takes effect on a new TLS handshake, so retrying over the
            # old session would 401 forever. The same goes for every
            # idle pooled connection (their sessions were handshaken
            # with the revoked cert): drain the pool so the retry —
            # and every later request — dials fresh instead of checking
            # out another stale session and failing terminally.
            self.config.exec_plugin.invalidate()
            self._discard_conn(conn)
            with self._conn_lock:
                stale, self._conns = self._conns, []
            for c in stale:
                self._discard_conn(c)
            return self._request(
                method, path, body=body, content_type=content_type,
                read_timeout=read_timeout, _auth_retry=False,
            )
        self._release_conn(conn)
        if resp.status >= 400:
            if resp.status == 409:
                raise ConflictError(data.decode("utf-8", "replace")[:200])
            raise ApiException(resp.status, data.decode("utf-8", "replace")[:200])
        return json.loads(data) if data else {}

    # -- nodes ----------------------------------------------------------
    def get_node(self, name: str) -> dict:
        return self._request("GET", f"/api/v1/nodes/{name}")

    def list_nodes(self, label_selector: Optional[str] = None) -> List[dict]:
        params: Dict[str, str] = {}
        if label_selector:
            params["labelSelector"] = label_selector
        return self._paged_list("/api/v1/nodes", params)

    def patch_node(self, name: str, patch: dict) -> dict:
        return self._request(
            "PATCH",
            f"/api/v1/nodes/{name}",
            body=patch,
            content_type="application/merge-patch+json",
        )

    def replace_node(self, name: str, node: dict) -> dict:
        return self._request("PUT", f"/api/v1/nodes/{name}", body=node)

    # -- pods -----------------------------------------------------------
    def list_pods(
        self,
        namespace: str,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
    ) -> List[dict]:
        params: Dict[str, str] = {}
        if label_selector:
            params["labelSelector"] = label_selector
        if field_selector:
            params["fieldSelector"] = field_selector
        return self._paged_list(f"/api/v1/namespaces/{namespace}/pods", params)

    def _paged_list(self, path: str, params: Dict[str, str]) -> List[dict]:
        """Chunked LIST following ``metadata.continue`` tokens, so a
        thousands-of-nodes fleet scan doesn't ask the API server for one
        giant response (client-go informer behavior; reference
        cmd/main.go:185-209 gets this from the ListWatch machinery)."""
        items: List[dict] = []
        cont: Optional[str] = None
        while True:
            page = dict(params, limit=str(self.list_page_limit))
            if cont:
                page["continue"] = cont
            resp = self._request("GET", path + "?" + urllib.parse.urlencode(page))
            items.extend(resp.get("items", []))
            cont = resp.get("metadata", {}).get("continue")
            if not cont:
                return items

    def delete_pod(self, namespace: str, name: str) -> None:
        self._request("DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def evict_pod(self, namespace: str, name: str) -> None:
        self._request(
            "POST",
            f"/api/v1/namespaces/{namespace}/pods/{name}/eviction",
            body={
                "apiVersion": "policy/v1",
                "kind": "Eviction",
                "metadata": {"name": name, "namespace": namespace},
            },
        )

    def create_event(self, namespace: str, event: dict) -> dict:
        return self._request(
            "POST", f"/api/v1/namespaces/{namespace}/events", body=event
        )

    # -- custom resources ------------------------------------------------
    def list_cluster_custom(
        self, group: str, version: str, plural: str
    ) -> List[dict]:
        return self._paged_list(f"/apis/{group}/{version}/{plural}", {})

    def get_cluster_custom(
        self, group: str, version: str, plural: str, name: str
    ) -> dict:
        return self._request("GET", f"/apis/{group}/{version}/{plural}/{name}")

    def patch_cluster_custom(
        self,
        group: str,
        version: str,
        plural: str,
        name: str,
        patch: dict,
        subresource: Optional[str] = None,
    ) -> dict:
        path = f"/apis/{group}/{version}/{plural}/{name}"
        if subresource:
            path += f"/{subresource}"
        return self._request(
            "PATCH", path, body=patch,
            content_type="application/merge-patch+json",
        )

    def list_events(self, namespace: str) -> List[dict]:
        resp = self._request(
            "GET", f"/api/v1/namespaces/{namespace}/events"
        )
        return resp.get("items", [])

    # -- leases (coordination.k8s.io/v1) ---------------------------------
    _LEASE_BASE = "/apis/coordination.k8s.io/v1/namespaces"

    def get_lease(self, namespace: str, name: str) -> dict:
        return self._request(
            "GET", f"{self._LEASE_BASE}/{namespace}/leases/{name}"
        )

    def create_lease(self, namespace: str, lease: dict) -> dict:
        return self._request(
            "POST", f"{self._LEASE_BASE}/{namespace}/leases", body=lease
        )

    def replace_lease(self, namespace: str, name: str,
                      lease: dict) -> dict:
        # PUT carries metadata.resourceVersion; the server 409s when it
        # moved — surfaced as ConflictError by _request
        return self._request(
            "PUT", f"{self._LEASE_BASE}/{namespace}/leases/{name}",
            body=lease,
        )

    # -- watch ----------------------------------------------------------
    def watch_nodes(
        self,
        name: Optional[str] = None,
        resource_version: Optional[str] = None,
        timeout_s: int = 300,
        _auth_retry: bool = True,
    ) -> Iterator[Tuple[str, dict]]:
        # bookmarks keep our resourceVersion current through other-object
        # churn, avoiding needless 410 re-lists at cluster scale
        params = {
            "watch": "true",
            "timeoutSeconds": str(timeout_s),
            "allowWatchBookmarks": "true",
        }
        if name:
            # node-scoped watch, exactly like the Go informer's fieldSelector
            # metadata.name=<node> (reference cmd/main.go:185-190)
            params["fieldSelector"] = f"metadata.name={name}"
        if resource_version is not None:
            params["resourceVersion"] = str(resource_version)
        path = "/api/v1/nodes?" + urllib.parse.urlencode(params)

        yield from self._stream_watch(
            path, timeout_s,
            retry=(
                (lambda: self.watch_nodes(
                    name=name, resource_version=resource_version,
                    timeout_s=timeout_s, _auth_retry=False,
                )) if _auth_retry else None
            ),
        )

    def watch_cluster_custom(
        self,
        group: str,
        version: str,
        plural: str,
        resource_version: Optional[str] = None,
        timeout_s: int = 300,
        _auth_retry: bool = True,
    ) -> Iterator[Tuple[str, dict]]:
        params = {"watch": "true", "timeoutSeconds": str(timeout_s)}
        if resource_version is not None:
            params["resourceVersion"] = str(resource_version)
        path = (f"/apis/{group}/{version}/{plural}?"
                + urllib.parse.urlencode(params))
        yield from self._stream_watch(
            path, timeout_s,
            retry=(
                (lambda: self.watch_cluster_custom(
                    group, version, plural,
                    resource_version=resource_version,
                    timeout_s=timeout_s, _auth_retry=False,
                )) if _auth_retry else None
            ),
        )

    def _stream_watch(self, path: str, timeout_s: int,
                      retry=None) -> Iterator[Tuple[str, dict]]:
        """Shared NDJSON watch transport: dial, 401 invalidate-and-retry
        (via ``retry``, which re-invokes the caller once), stream until
        the server-side timeout closes the connection. Watch STARTS
        count against the flow-control bucket (client-go does the
        same) — a hot relist loop is exactly a request storm; the
        long-lived stream itself is free."""
        self._acquire_token()
        try:
            conn = self._connect(read_timeout=timeout_s + 30)
        except ExecCredentialError as e:
            raise ApiException(0, f"exec credential failure: {e}") from e
        try:
            try:
                conn.request("GET", path, headers=self._headers())
                resp = conn.getresponse()
            except ExecCredentialError as e:
                raise ApiException(0, f"exec credential failure: {e}") from e
            except OSError as e:
                raise ApiException(0, f"transport error: {e}") from e
            if (resp.status == 401 and retry is not None
                    and self.config.exec_plugin):
                # same invalidate-and-retry as _request: a revoked cached
                # exec credential must not burn the watcher's consecutive-
                # error budget when one plugin re-run fixes it
                self.config.exec_plugin.invalidate()
                resp.read()
                yield from retry()
                return
            if resp.status >= 400:
                raise ApiException(resp.status, resp.read().decode("utf-8", "replace")[:200])
            # newline-delimited JSON event stream
            buf = b""
            while True:
                try:
                    chunk = resp.read1(65536)
                except (socket.timeout, ssl.SSLError) as e:
                    raise ApiException(0, f"watch read timeout: {e}")
                if not chunk:
                    return  # server closed (watch timeout elapsed)
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    evt = json.loads(line)
                    if evt.get("type") == "ERROR":
                        status = evt.get("object", {})
                        raise ApiException(
                            int(status.get("code", 500)),
                            status.get("message", "watch error"),
                        )
                    yield evt["type"], evt["object"]
        finally:
            conn.close()
