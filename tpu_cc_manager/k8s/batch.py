"""Write-coalescing I/O layer between the reconcile path and the API.

BENCH_NOTES r03–r05 established that the flip hot path is node-write
round trips, not device work: a flip historically cost ~five separate
writes (state label, taint add, taint clear, evidence annotation,
doctor annotation) against a contended API server. This module is the
structural fix ROADMAP item 4 calls for: same-node mutations issued
around one reconcile merge into at most two HTTP writes.

:class:`NodePatchBatcher` owns ONE node's pending mutations and offers
three delivery paths, strongest ordering first:

1. **Synchronous ordered writes** (``write_labels_now``) — the
   fail-secure ``cc.mode.state`` write. Sent immediately as one JSON
   merge patch that also CARRIES everything pending, so the ordered
   write costs the same round trip it always did while draining the
   coalescing queue for free. Failure propagates to the caller
   (fail-secure semantics are the caller's contract) and pending
   mutations are retained, never half-applied — a merge patch is atomic
   server-side.
2. **Carrier folds** (``fold_into_node`` / ``mark_folded``) — the flip
   taint's CAS replaces already hold the whole node object in hand;
   folding pending label/annotation mutations into that object makes
   the taint write the evidence/doctor publication too. The caller
   reports landing via ``mark_folded`` (a conflicted CAS retry simply
   re-folds).
3. **Deferred coalescing publications** (``defer`` + ``flush`` /
   ``maybe_flush``) — evidence and doctor documents are keyed
   publications where only the NEWEST generation ever matters: a newer
   ``defer`` under the same key replaces an unsent older one (counted —
   ``coalesced_total``; that drop is by design and loss-accounted, not
   silent). Whatever hasn't ridden a carrier is flushed with bounded
   retry/backoff; a publication that exhausts its retry budget is
   dropped LOUDLY (``dropped_total`` + ``on_drop``) and the owner's
   generation bookkeeping (agent.py ``_evidence_published_gen``)
   notices published < wanted and re-defers a fresh build from its
   idle tick — the newest generation always lands eventually.

What never batches: taint list edits themselves (CAS replace,
order-critical), drain pause/restore labels (the pod-wait poll reads
them), and the fail-secure state write never waits behind the queue.
Full contract: docs/io.md.

Thread-safety: every mutation of pending state happens under ``_lock``;
HTTP writes happen OUTSIDE the lock (ccaudit blocking-under-lock), so a
flush racing a carrier fold can at worst deliver the same newest
payload twice — an idempotent merge, not a reorder.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpu_cc_manager import labels as L
from tpu_cc_manager.flightrec import get_recorder
from tpu_cc_manager.trace import Tracer, get_tracer

log = logging.getLogger("tpu-cc-manager.k8s.batch")

#: (key, gen) pairs a carrier write is transporting; handed back to
#: ``mark_folded`` when the carrier lands.
FoldToken = List[Tuple[str, int]]


class _Pending:
    """One key's newest unsent publication."""

    __slots__ = ("gen", "labels", "annotations", "on_published", "retries")

    def __init__(
        self,
        gen: int,
        labels: Optional[Dict[str, Optional[str]]],
        annotations: Optional[Dict[str, Optional[str]]],
        on_published: Optional[Callable[[int], None]],
    ) -> None:
        self.gen = gen
        self.labels = dict(labels or {})
        self.annotations = dict(annotations or {})
        self.on_published = on_published
        self.retries = 0


class NodePatchBatcher:
    """Per-node write coalescer (see module docstring for the model)."""

    #: a publication that failed this many direct flushes is dropped
    #: (accounted); the owner's generation bookkeeping re-defers fresh
    MAX_RETRIES = 8
    #: exponential backoff for failed flushes: base * 2^(n-1), capped
    BACKOFF_BASE_S = 0.2
    BACKOFF_CAP_S = 30.0

    def __init__(
        self,
        kube: Any,
        node_name: str,
        *,
        flush_interval_s: float = 0.25,
        tracer: Optional[Tracer] = None,
        on_coalesced: Optional[Callable[[str], None]] = None,
        on_retry: Optional[Callable[[str], None]] = None,
        on_drop: Optional[Callable[[str], None]] = None,
        recorder: Optional[Any] = None,
    ) -> None:
        self.kube = kube
        self.node_name = node_name
        self.flush_interval_s = flush_interval_s
        self._tracer = tracer or get_tracer()
        self._on_coalesced = on_coalesced
        self._on_retry = on_retry
        self._on_drop = on_drop
        #: flight recorder the publish-loss events note into; None =
        #: the process-wide one at event time (the agent points that at
        #: its own black box via flightrec.set_recorder; simlab
        #: replicas inject theirs — a per-replica batcher noting into
        #: the process default would be invisible to the fleet stitch)
        self._recorder = recorder
        self._lock = threading.Lock()
        self._pending: Dict[str, _Pending] = {}
        self._gen_seq: Dict[str, int] = {}
        #: monotonic time before which maybe_flush stays quiet (set by
        #: failed flushes — the backoff — and successful ones — the
        #: minimum flush spacing)
        self._next_flush_at = 0.0
        self._consecutive_failures = 0
        # accounting (all under _lock; read via stats())
        self.coalesced_total = 0  #: superseded-before-send publications
        self.folded_total = 0  #: publications that rode a carrier write
        self.flushed_total = 0  #: publications delivered by direct flush
        self.retries_total = 0  #: failed direct-flush write attempts
        self.dropped_total = 0  #: publications dropped after MAX_RETRIES

    # ------------------------------------------------------------ deferred
    def next_gen(self, key: str) -> int:
        """Allocate the next generation number for ``key`` (monotonic
        per batcher; callers carrying their own generation counters —
        the agent's evidence machinery — pass theirs to defer)."""
        with self._lock:
            gen = self._gen_seq.get(key, 0) + 1
            self._gen_seq[key] = gen
            return gen

    def defer(
        self,
        key: str,
        *,
        labels: Optional[Dict[str, Optional[str]]] = None,
        annotations: Optional[Dict[str, Optional[str]]] = None,
        gen: Optional[int] = None,
        on_published: Optional[Callable[[int], None]] = None,
    ) -> int:
        """Queue a coalescing publication: the newest ``defer`` under a
        key wins; an unsent older one is superseded (counted). Returns
        the generation this publication carries. Never blocks, never
        raises."""
        coalesced = False
        with self._lock:
            if gen is None:
                gen = self._gen_seq.get(key, 0) + 1
            self._gen_seq[key] = max(self._gen_seq.get(key, 0), gen)
            if key in self._pending:
                coalesced = True
                self.coalesced_total += 1
            first = not self._pending
            self._pending[key] = _Pending(gen, labels, annotations,
                                          on_published)
            # schedule a direct flush one flush window out: the window
            # is the carrier-write grace period — a reconcile's taint/
            # state write usually arrives first and transports this for
            # free. The first pending item arms a fresh schedule; later
            # ones may only PULL it earlier (a long failure backoff is
            # shortened for fresh data — backoff punishes failed
            # WRITES, not new generations).
            due = time.monotonic() + self.flush_interval_s
            self._next_flush_at = (
                due if first else min(self._next_flush_at, due)
            )
        if coalesced and self._on_coalesced is not None:
            self._notify(self._on_coalesced, key)
        return gen

    def has_pending(self, key: Optional[str] = None) -> bool:
        with self._lock:
            if key is not None:
                return key in self._pending
            return bool(self._pending)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "pending": len(self._pending),
                "coalesced": self.coalesced_total,
                "folded": self.folded_total,
                "flushed": self.flushed_total,
                "retries": self.retries_total,
                "dropped": self.dropped_total,
            }

    # ------------------------------------------------------------ carriers
    def fold_into_node(self, node: dict) -> FoldToken:
        """Merge every pending mutation into a node object about to be
        CAS-replaced (mutates ``node`` in place). Returns the token to
        hand to :meth:`mark_folded` once that replace LANDED; a
        conflicted attempt just folds again into the fresh read."""
        token: FoldToken = []
        with self._lock:
            for key, p in self._pending.items():
                meta = node.setdefault("metadata", {})
                # a None value means delete-key (merge-patch semantics on
                # the flush path); on a full replace body that translates
                # to the key being ABSENT, never a literal null
                for field, muts in (("labels", p.labels),
                                    ("annotations", p.annotations)):
                    if not muts:
                        continue
                    target = meta.setdefault(field, {})
                    for k, v in muts.items():
                        if v is None:
                            target.pop(k, None)
                        else:
                            target[k] = v
                token.append((key, p.gen))
        return token

    def mark_folded(self, token: FoldToken) -> None:
        """A carrier write holding ``token``'s publications landed:
        retire exactly those generations (a newer defer that arrived
        mid-write stays pending) and fire their callbacks."""
        if not token:
            return
        callbacks: List[Tuple[Callable[[int], None], int]] = []
        with self._lock:
            for key, gen in token:
                p = self._pending.get(key)
                if p is not None and p.gen == gen:
                    del self._pending[key]
                    self.folded_total += 1
                    if p.on_published is not None:
                        callbacks.append((p.on_published, gen))
        for cb, gen in callbacks:
            self._notify(cb, gen)

    def fold_into_patch(self, patch: dict) -> FoldToken:
        """Merge pending mutations into an outgoing merge-patch body
        (mutates ``patch``); same token contract as fold_into_node.
        The CALLER's keys win on conflict — an ordered write's payload
        is never overridden by a deferred one."""
        token: FoldToken = []
        meta = patch.setdefault("metadata", {})
        caller_labels = dict(meta.get("labels") or {})
        caller_ann = dict(meta.get("annotations") or {})
        with self._lock:
            for key, p in self._pending.items():
                if p.labels:
                    merged = dict(p.labels)
                    merged.update(caller_labels)
                    caller_labels = merged
                if p.annotations:
                    merged = dict(p.annotations)
                    merged.update(caller_ann)
                    caller_ann = merged
                token.append((key, p.gen))
        if caller_labels:
            meta["labels"] = caller_labels
        if caller_ann:
            meta["annotations"] = caller_ann
        return token

    # ----------------------------------------------------- ordered writes
    def write_labels_now(self, labels: Dict[str, Optional[str]]) -> None:
        """Synchronous ordered label write (the fail-secure state path):
        ONE merge patch carrying ``labels`` plus everything pending.
        Raises on failure — the caller owns fail-secure semantics — and
        pending publications are retained for the next carrier/flush
        (the merge patch is atomic server-side: on failure NOTHING
        landed, so there is no half-applied state to reason about)."""
        patch: dict = {"metadata": {"labels": dict(labels)}}
        token = self.fold_into_patch(patch)
        self._write_patch(patch)  # raises to the caller on failure
        self.mark_folded(token)

    def write_state_label(self, value: str) -> None:
        """Fail-secure observed-state publish: ONE synchronous ordered
        write of the ``cc.mode.state`` label (``write_labels_now``
        semantics — raises on failure, doubles as a publication
        carrier). The one definition of the log+write pair the agent
        and simlab replicas both publish through."""
        log.info("setting %s=%s on node %s", L.CC_MODE_STATE_LABEL,
                 value, self.node_name)
        self.write_labels_now({L.CC_MODE_STATE_LABEL: value})

    # --------------------------------------------------------------- flush
    def maybe_flush(self) -> None:
        """Idle-tick entry point: flush pending publications when due
        (respects the flush window and failure backoff). Never raises."""
        with self._lock:
            if not self._pending or time.monotonic() < self._next_flush_at:
                return
        self.flush()

    def flush(self) -> bool:
        """Deliver everything pending in ONE write now (unconditional;
        maybe_flush is the window/backoff-respecting entry point).
        Returns True when nothing remains pending. Failures are
        absorbed into the retry/backoff accounting (never raises)."""
        with self._lock:
            if not self._pending:
                return True
            snapshot = [(k, p) for k, p in self._pending.items()]
        labels: Dict[str, Optional[str]] = {}
        ann: Dict[str, Optional[str]] = {}
        for _, p in snapshot:
            labels.update(p.labels)
            ann.update(p.annotations)
        try:
            with self._tracer.span("publish_flush",
                                   keys=[k for k, _ in snapshot]):
                self._write_split(labels, ann)
        except Exception as e:
            self._record_flush_failure(snapshot, e)
            return False
        callbacks: List[Tuple[Callable[[int], None], int]] = []
        with self._lock:
            self._consecutive_failures = 0
            self._next_flush_at = time.monotonic() + self.flush_interval_s
            for key, p in snapshot:
                cur = self._pending.get(key)
                if cur is not None and cur.gen == p.gen:
                    del self._pending[key]
                    self.flushed_total += 1
                    if p.on_published is not None:
                        callbacks.append((p.on_published, p.gen))
        for cb, gen in callbacks:
            self._notify(cb, gen)
        return not self.has_pending()

    def close(self) -> None:
        """Best-effort final flush (shutdown)."""
        self.flush()

    # ------------------------------------------------------------ plumbing
    def _write_patch(self, patch: dict) -> None:
        meta = patch.get("metadata") or {}
        self._write_split(meta.get("labels") or {},
                          meta.get("annotations") or {})

    def _write_split(
        self,
        labels: Dict[str, Optional[str]],
        ann: Dict[str, Optional[str]],
    ) -> None:
        """One node write for the combined payload, via the narrowest
        client verb that covers it (keeps the KubeClient convenience
        surface — and everything tests/fakes layer onto it — honest)."""
        if labels and ann:
            self.kube.patch_node(self.node_name, {
                "metadata": {"labels": labels, "annotations": ann},
            })
        elif ann:
            self.kube.set_node_annotations(self.node_name, ann)
        elif labels:
            self.kube.set_node_labels(self.node_name, labels)

    def _record_flush_failure(
        self, snapshot: List[Tuple[str, _Pending]], exc: Exception
    ) -> None:
        dropped: List[str] = []
        retried: List[str] = []
        with self._lock:
            self._consecutive_failures += 1
            backoff = min(
                self.BACKOFF_BASE_S * 2 ** (self._consecutive_failures - 1),
                self.BACKOFF_CAP_S,
            )
            self._next_flush_at = time.monotonic() + backoff
            for key, p in snapshot:
                cur = self._pending.get(key)
                if cur is None or cur.gen != p.gen:
                    continue  # superseded mid-write; the newer one owns retries
                cur.retries += 1
                self.retries_total += 1
                retried.append(key)
                if cur.retries >= self.MAX_RETRIES:
                    del self._pending[key]
                    self.dropped_total += 1
                    dropped.append(key)
        log.warning(
            "publish flush for %s failed (%s); retrying %s in %.1fs%s",
            self.node_name, exc, retried, backoff,
            f"; DROPPED after retry budget: {dropped}" if dropped else "",
        )
        # the black box keeps the loss accounting next to the spans it
        # explains: a dump after a publish storm shows WHICH keys were
        # retried/dropped, not just the counters' totals
        (self._recorder or get_recorder()).note(
            "publish_flush_failed", node=self.node_name,
            error=f"{type(exc).__name__}: {exc}", retried=retried,
            dropped=dropped, backoff_s=round(backoff, 2),
        )
        for key in retried:
            if self._on_retry is not None:
                self._notify(self._on_retry, key)
        for key in dropped:
            if self._on_drop is not None:
                self._notify(self._on_drop, key)

    @staticmethod
    def _notify(cb: Callable[[Any], None], arg: Any) -> None:
        try:
            cb(arg)
        except Exception:
            # observability/bookkeeping hooks must never sink a write
            log.debug("batcher callback failed", exc_info=True)
