"""First-party Kubernetes API access.

The reference leans on two heavyweight dependencies for this: ~45 MB of
vendored client-go for the Go agent (reference go.mod:7-13, vendor/) and
the ``kubernetes`` Python client for the Python agent (reference
requirements.txt:2). This build replaces both with one small stdlib
client speaking the REST API directly — node get/list/patch/replace, pod
list/delete/evict, and the watch protocol (streamed JSON events with
resourceVersion resume and 410 handling), which is the *entire* API
surface the agents use (SURVEY.md §3.5).

- :class:`~tpu_cc_manager.k8s.client.KubeClient` — the interface.
- :class:`~tpu_cc_manager.k8s.client.HttpKubeClient` — stdlib
  http.client + ssl impl; in-cluster service-account config or kubeconfig.
- :class:`~tpu_cc_manager.k8s.fake.FakeKube` — in-memory clientset with a
  real watch implementation (rv history, 410 compaction, error
  injection) for the test pyramid.
- :mod:`~tpu_cc_manager.k8s.apiserver` — an HTTP server exposing a
  FakeKube over the real wire protocol, for integration tests of the
  C++ agent / bash engine / HttpKubeClient, and for the bench.
"""

from tpu_cc_manager.k8s.client import (
    ApiException,
    ConflictError,
    HttpKubeClient,
    KubeClient,
)
from tpu_cc_manager.k8s.fake import FakeKube

__all__ = [
    "ApiException",
    "ConflictError",
    "HttpKubeClient",
    "KubeClient",
    "FakeKube",
]
