"""Sync façade over the asyncio kube core — one loop thread per process.

The reconcile stack (`agent.py`, `engine.py`, `flipexec.py`, the
batcher, simlab replicas) is synchronous by contract and stays that
way: this module hosts ONE event loop on a daemon thread and exposes

- :func:`get_bridge` — the process-wide :class:`AioBridge`, created
  lazily (one loop thread per process, the ISSUE 13 ownership rule:
  the loop thread owns every ``AsyncKubeClient``'s state; no other
  thread touches it except through ``submit``);
- :class:`AioBridge` — ``call`` (run a coroutine, block for its
  result), ``submit`` (schedule a coroutine OR a blocking callable,
  get a ``concurrent.futures.Future``), ``gather`` (wait for many);
- :class:`SyncKubeFacade` — a full :class:`~…k8s.client.KubeClient`
  whose every verb round-trips through the loop. Calls block the
  calling thread until the response lands, so **at concurrency 1 the
  façade is order-identical to the threaded client**: submit order ==
  completion order, and trace spans (opened on the CALLING thread,
  around the blocking call) parent and sequence byte-identically —
  pinned by tests/test_engine_parallel.py.

The engine's stage/holder-scan overlap
(`flipexec.submit_overlapped`/`join_overlapped`) rides the same
bridge: the side callable runs on the loop's default executor via
``submit``, so one thread pool serves every "hide this synchronous
wait" need in the process.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import queue
import threading
from typing import (
    Any,
    AsyncIterator,
    Callable,
    Coroutine,
    Iterator,
    List,
    Optional,
    Tuple,
)

from tpu_cc_manager.k8s.aio import AsyncKubeClient
from tpu_cc_manager.k8s.client import KubeClient, KubeConfig

log = logging.getLogger("tpu-cc-manager.k8s.aio-bridge")

_bridge: Optional["AioBridge"] = None
_bridge_lock = threading.Lock()


class AioBridge:
    """One event loop on one daemon thread; everything else submits."""

    def __init__(self, name: str = "cc-aio-loop") -> None:
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()
        # drain the loop's tasks before the interpreter tears the
        # daemon thread down: abandoned reader tasks would otherwise
        # spray "Task was destroyed but it is pending!" into every
        # CLI/bench exit log
        import atexit

        atexit.register(self.shutdown)

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def shutdown(self, timeout: float = 2.0) -> None:
        """Cancel and await every loop task, then stop the loop. Safe
        to call more than once; registered atexit."""
        if not self.loop.is_running():
            return

        async def _drain() -> None:
            tasks = [
                t for t in asyncio.all_tasks()
                if t is not asyncio.current_task()
            ]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

        try:
            asyncio.run_coroutine_threadsafe(
                _drain(), self.loop
            ).result(timeout)
            self.loop.call_soon_threadsafe(self.loop.stop)
            self._thread.join(timeout)
        except Exception:
            log.debug("bridge shutdown incomplete", exc_info=True)

    # ------------------------------------------------------------ calls
    def call(self, coro: "Coroutine[Any, Any, Any]",
             timeout: Optional[float] = None) -> Any:
        """Run a coroutine on the loop; block for (and return) its
        result. The ONE way sync code reaches async state."""
        return asyncio.run_coroutine_threadsafe(
            coro, self.loop
        ).result(timeout)

    def submit(self, fn: Callable, *args: Any, **kwargs: Any
               ) -> "concurrent.futures.Future":
        """Schedule work without waiting: a coroutine function runs as
        a loop task; a plain callable runs on the loop's default
        executor (a thread pool — for synchronous waits worth hiding,
        like the flip path's holder scan). Returns a concurrent
        Future; pair with :meth:`gather`."""
        if asyncio.iscoroutinefunction(fn):
            return asyncio.run_coroutine_threadsafe(
                fn(*args, **kwargs), self.loop
            )
        out: "concurrent.futures.Future" = concurrent.futures.Future()

        def _dispatch() -> None:
            exec_fut = self.loop.run_in_executor(
                None, lambda: fn(*args, **kwargs)
            )

            def _done(f: "asyncio.Future") -> None:
                if f.cancelled():
                    out.cancel()
                elif f.exception() is not None:
                    out.set_exception(f.exception())
                else:
                    # ccaudit: allow-missing-deadline(done-callback: f has already settled — this result() returns immediately, it never waits)
                    out.set_result(f.result())

            exec_fut.add_done_callback(_done)

        self.loop.call_soon_threadsafe(_dispatch)
        return out

    @staticmethod
    def gather(futures: List["concurrent.futures.Future"],
               timeout: Optional[float] = None) -> List[Any]:
        """Block until every future resolves; first exception wins
        AFTER all have settled (nothing is abandoned mid-flight —
        the flip path's fail-secure join relies on this)."""
        concurrent.futures.wait(futures, timeout=timeout)
        return [f.result(timeout=0) for f in futures]


def get_bridge() -> AioBridge:
    """The process-wide loop thread (lazily created)."""
    global _bridge
    with _bridge_lock:
        if _bridge is None:
            _bridge = AioBridge()
        return _bridge


#: watch-pump sentinel: clean end of stream
_DONE: object = object()


class SyncKubeFacade(KubeClient):
    """`KubeClient` implemented by round-tripping every verb through
    an :class:`AsyncKubeClient` on the bridge loop. Thread-safe: any
    number of threads (flip executor workers, simlab replicas sharing
    one façade in shared-loop mode) may call concurrently — their
    requests multiplex onto the loop's pipelined connection pool and
    each caller blocks only on its own response future."""

    def __init__(self, config: KubeConfig,
                 *,
                 max_conns: Optional[int] = None,
                 window: Optional[int] = None,
                 qps: Optional[float] = None,
                 burst: Optional[int] = None,
                 bridge: Optional[AioBridge] = None,
                 aio: Optional[AsyncKubeClient] = None) -> None:
        self.config = config
        self.bridge = bridge or get_bridge()
        self.aio = aio or AsyncKubeClient(
            config, max_conns=max_conns, window=window,
            qps=qps, burst=burst,
        )

    # ------------------------------------------------- throttle surface
    # (same attribute contract as HttpKubeClient, so the simlab runner
    # and fault injector drive either core interchangeably)
    @property
    def throttle_waits(self) -> int:
        # ccaudit: allow-loop-affinity(GIL-atomic read of a loop-written monotonic counter; a bridge hop per metrics scrape would cost more than the staleness it buys)
        return self.aio.throttle_waits

    @property
    def throttle_wait_s_total(self) -> float:
        # ccaudit: allow-loop-affinity(GIL-atomic read of a loop-written float accumulator; snapshot staleness is fine for metrics)
        return self.aio.throttle_wait_s_total

    def add_throttle_observer(self, fn: Callable[[float], None]) -> None:
        self.aio.add_throttle_observer(fn)

    def add_rtt_observer(self, fn: Callable[[str, str, float], None]) -> None:
        self.aio.add_rtt_observer(fn)

    def add_queue_reject_observer(self, fn: Callable[[], None]) -> None:
        self.aio.add_queue_reject_observer(fn)

    def set_qps(self, qps: float, burst: Optional[int] = None) -> None:
        # swap the bucket ON the loop: bucket state is loop-confined
        self.bridge.loop.call_soon_threadsafe(
            self.aio.set_qps, qps, burst
        )

    def stats(self) -> dict:
        return self.aio.stats()

    def close(self) -> None:
        try:
            self.bridge.call(self.aio.aclose(), timeout=5)
        except Exception:
            log.debug("async client close failed", exc_info=True)

    # ------------------------------------------------------------ verbs
    def get_node(self, name: str) -> dict:
        return self.bridge.call(self.aio.get_node(name))

    def list_nodes(self, label_selector: Optional[str] = None) -> List[dict]:
        return self.bridge.call(self.aio.list_nodes(label_selector))

    def patch_node(self, name: str, patch: dict) -> dict:
        return self.bridge.call(self.aio.patch_node(name, patch))

    def replace_node(self, name: str, node: dict) -> dict:
        return self.bridge.call(self.aio.replace_node(name, node))

    def list_pods(self, namespace: str,
                  label_selector: Optional[str] = None,
                  field_selector: Optional[str] = None) -> List[dict]:
        return self.bridge.call(self.aio.list_pods(
            namespace, label_selector, field_selector
        ))

    def delete_pod(self, namespace: str, name: str) -> None:
        self.bridge.call(self.aio.delete_pod(namespace, name))

    def evict_pod(self, namespace: str, name: str) -> None:
        self.bridge.call(self.aio.evict_pod(namespace, name))

    def create_event(self, namespace: str, event: dict) -> dict:
        return self.bridge.call(self.aio.create_event(namespace, event))

    def list_events(self, namespace: str) -> List[dict]:
        return self.bridge.call(self.aio.list_events(namespace))

    def get_lease(self, namespace: str, name: str) -> dict:
        return self.bridge.call(self.aio.get_lease(namespace, name))

    def create_lease(self, namespace: str, lease: dict) -> dict:
        return self.bridge.call(self.aio.create_lease(namespace, lease))

    def replace_lease(self, namespace: str, name: str,
                      lease: dict) -> dict:
        return self.bridge.call(self.aio.replace_lease(
            namespace, name, lease
        ))

    def list_cluster_custom(self, group: str, version: str,
                            plural: str) -> List[dict]:
        return self.bridge.call(self.aio.list_cluster_custom(
            group, version, plural
        ))

    def get_cluster_custom(self, group: str, version: str,
                           plural: str, name: str) -> dict:
        return self.bridge.call(self.aio.get_cluster_custom(
            group, version, plural, name
        ))

    def patch_cluster_custom(self, group: str, version: str,
                             plural: str, name: str, patch: dict,
                             subresource: Optional[str] = None) -> dict:
        return self.bridge.call(self.aio.patch_cluster_custom(
            group, version, plural, name, patch, subresource=subresource
        ))

    # ------------------------------------------------------------ watch
    def watch_nodes(self, name: Optional[str] = None,
                    resource_version: Optional[str] = None,
                    timeout_s: int = 300,
                    ) -> Iterator[Tuple[str, dict]]:
        return self._pump_watch(self.aio.watch_nodes(
            name=name, resource_version=resource_version,
            timeout_s=timeout_s,
        ), timeout_s)

    def watch_cluster_custom(self, group: str, version: str,
                             plural: str,
                             resource_version: Optional[str] = None,
                             timeout_s: int = 300,
                             ) -> Iterator[Tuple[str, dict]]:
        return self._pump_watch(self.aio.watch_cluster_custom(
            group, version, plural,
            resource_version=resource_version, timeout_s=timeout_s,
        ), timeout_s)

    def _pump_watch(self, agen: "AsyncIterator[Tuple[str, dict]]",
                    timeout_s: int,
                    ) -> Iterator[Tuple[str, dict]]:
        """Bridge an async event stream to a plain sync iterator: a
        loop task pumps into a queue; the consuming thread blocks on
        it. Abandoning the iterator (watcher stop, GC) cancels the
        pump task so the dedicated watch connection is reclaimed."""
        # ccaudit: allow-unbounded-queue(cross-thread hand-off for ONE watch stream: a bounded put would stall the shared loop thread behind a slow consumer, wedging every other bridge user; the stream itself is bounded by the server-side watch timeoutSeconds)
        q: "queue.Queue" = queue.Queue()

        async def pump() -> None:
            try:
                async for item in agen:
                    q.put(item)
                q.put(_DONE)
            except asyncio.CancelledError:
                q.put(_DONE)
                raise
            except BaseException as e:  # ApiException included
                q.put(e)

        fut = asyncio.run_coroutine_threadsafe(pump(), self.bridge.loop)
        try:
            while True:
                # bounded block so a dead pump can never hang a watcher
                # thread past the stream's own lifetime
                try:
                    item = q.get(timeout=timeout_s + 60)
                except queue.Empty:
                    from tpu_cc_manager.k8s.client import ApiException

                    raise ApiException(
                        0, "watch bridge stalled past the stream "
                           "timeout"
                    ) from None
                if item is _DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            fut.cancel()
