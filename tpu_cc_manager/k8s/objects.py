"""Tiny object helpers shared by the fake store, the API server, and tests."""

from __future__ import annotations

import copy
from typing import Dict, Optional


def make_node(name: str, labels: Optional[Dict[str, str]] = None,
              annotations: Optional[Dict[str, str]] = None) -> dict:
    return {
        "kind": "Node",
        "apiVersion": "v1",
        "metadata": {
            "name": name,
            "labels": dict(labels or {}),
            "annotations": dict(annotations or {}),
            "resourceVersion": "0",
        },
        "spec": {},
        "status": {},
    }


def make_pod(name: str, namespace: str = "default",
             labels: Optional[Dict[str, str]] = None,
             node_name: Optional[str] = None) -> dict:
    return {
        "kind": "Pod",
        "apiVersion": "v1",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": dict(labels or {}),
            "resourceVersion": "0",
        },
        "spec": {"nodeName": node_name},
        "status": {"phase": "Running"},
    }


def merge_patch(target: dict, patch: dict) -> dict:
    """RFC 7386 JSON merge patch: dicts merge recursively, null deletes.

    This is the patch flavor both agents use for labels (the reference
    patches ``{"metadata": {"labels": {...}}}``,
    gpu_operator_eviction.py:165-171).
    """
    out = copy.deepcopy(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        elif isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = merge_patch(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out


def match_selector(labels: Dict[str, str], selector: Optional[str]) -> bool:
    """Subset of k8s label-selector syntax used by the agents:
    ``k=v``, ``k==v``, ``k!=v``, bare ``k`` (exists), comma-joined."""
    if not selector:
        return True
    for term in selector.split(","):
        term = term.strip()
        if not term:
            continue
        if "!=" in term:
            k, v = term.split("!=", 1)
            if labels.get(k.strip()) == v.strip():
                return False
        elif "==" in term:
            k, v = term.split("==", 1)
            if labels.get(k.strip()) != v.strip():
                return False
        elif "=" in term:
            k, v = term.split("=", 1)
            if labels.get(k.strip()) != v.strip():
                return False
        else:
            if term not in labels:
                return False
    return True
