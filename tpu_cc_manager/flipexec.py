"""Bounded-concurrency flip executor — overlap the per-device stalls.

The reference flips devices one at a time (reference main.py:258-311)
and the engine inherited that shape, so a multi-chip host paid
N × (stage + reset + wait_ready + verify) even though the dominant cost
— the post-reset boot wait (real_chip_flip_s decomposition, BENCH_NOTES
r05) — is pure waiting that overlaps perfectly across devices. This
module is the overlap: each plan item's full per-device sequence runs on
a worker thread, with a bounded pool so a 256-chip host doesn't spawn
256 resets at once.

Contract (docs/engine.md states it for the whole engine):

- ``concurrency <= 1`` (or a single item) runs the items serially in the
  CALLING thread — the historical loop, byte-identical in trace-span
  order, with its fail-stop semantics: the first failure leaves every
  later item untouched ("skipped").
- ``concurrency > 1`` runs up to that many items at once. The first
  failure sets an abort flag: **in-flight items run to completion of
  their own sequence** (a device is never abandoned mid-reset —
  half-applied hardware state is worse than a slow failure), while
  **not-yet-started items observe the flag and are skipped untouched**.
- :class:`~tpu_cc_manager.device.base.DeviceError` from an item is a
  *failure outcome* (the engine logs it and fails the flip); any other
  exception is re-raised — first in item order, but only **after** every
  in-flight sibling completed — preserving the serial path's
  unexpected-failure surface (engine._drain_wrapped catches it and
  publishes ``cc.mode.state=failed``).
- Span parenting survives the thread hop: the submitting thread's
  current span is adopted by every worker (trace.Tracer.adopt), so
  per-device ``flip``/``stage``/``reset``/``wait_ready``/``verify``
  spans nest under the reconcile exactly as they did serially.

The knob: ``TPU_CC_FLIP_CONCURRENCY`` (or the engine's constructor
override). Unset → ``min(4, plan size)``; ``1`` → the serial loop.

Lock discipline note (ccaudit blocking-under-lock): ``Future.result()``
and the executor shutdown are blocking waits on OTHER threads — this
module deliberately holds no lock across them, and the analyzer's
executor rule (docs/analysis.md) keeps it that way everywhere else too.
"""

from __future__ import annotations

import logging
import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from contextlib import ExitStack
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, TypeVar

from tpu_cc_manager.device.base import DeviceError
from tpu_cc_manager.trace import Tracer

log = logging.getLogger("tpu-cc-manager.flipexec")

T = TypeVar("T")
S = TypeVar("S")

#: Environment knob; ``1`` restores the serial per-device loop exactly.
ENV_KNOB = "TPU_CC_FLIP_CONCURRENCY"

#: Default ceiling when the knob is unset: enough to overlap the boot
#: waits of a typical 4-chip host without turning an 8-chip reset into
#: a host-wide power/thermal event.
DEFAULT_CAP = 4

#: FlipOutcome.status values.
OK = "ok"
FAILED = "failed"
SKIPPED = "skipped"


def flip_concurrency_knob(override: Optional[int] = None) -> int:
    """Resolve the UNCLAMPED flip-concurrency knob (the worker-pool
    ceiling a persistent executor should be sized to): ``override``
    (the engine's constructor knob) wins over the
    ``TPU_CC_FLIP_CONCURRENCY`` environment knob; unset/empty means
    ``DEFAULT_CAP``. Invalid values raise DeviceError so a typo'd
    DaemonSet env fails the flip loudly (state label ``failed``)
    instead of silently picking some cap."""
    cap = override
    if cap is None:
        raw = os.environ.get(ENV_KNOB, "").strip()
        if raw:
            try:
                cap = int(raw)
            except ValueError:
                raise DeviceError(
                    f"invalid {ENV_KNOB} {raw!r}: expected a positive integer"
                ) from None
    if cap is None:
        cap = DEFAULT_CAP
    if cap < 1:
        # name the knob the bad value actually came from
        source = "flip_concurrency override" if override is not None else ENV_KNOB
        raise DeviceError(
            f"invalid {source}={cap}: expected a positive integer"
        )
    return cap


def flip_concurrency(n_items: int, override: Optional[int] = None) -> int:
    """Effective flip concurrency for a plan of ``n_items``: the knob
    (see :func:`flip_concurrency_knob`) clamped to the plan size."""
    return max(1, min(flip_concurrency_knob(override), n_items))


@dataclass
class FlipOutcome:
    """Terminal state of one plan item after the executor ran it."""

    label: str  #: device path (display / logging key)
    status: str  #: OK | FAILED | SKIPPED
    #: engine-facing failure text; None for verify mismatches, which the
    #: flip sequence already logged (and marked on the span) in detail
    error: Optional[str] = None
    #: the exception that failed the item, when one was raised
    exception: Optional[BaseException] = None


def _note_failures(outcomes: Sequence[FlipOutcome],
                   recorder: Optional[Any]) -> None:
    """Record every non-OK item disposition in the flight recorder
    (flightrec.py, ISSUE 8): after a multi-chip failure the black box
    answers "which device failed, and which siblings were skipped vs
    ran to completion" without correlating log lines. OK items stay
    out of the ring — failures are the signal."""
    if recorder is None:
        return
    for o in outcomes:
        if o.status != OK:
            recorder.note(
                "flip_item", device=o.label, status=o.status,
                error=o.error,
            )


def _reraise_unexpected(outcomes: Sequence[FlipOutcome]) -> None:
    """Re-raise the first (in item order) non-DeviceError exception.

    DeviceError is the expected failure currency — the engine logs it
    and fails the flip. Anything else is a bug surface and must keep
    propagating to _drain_wrapped's unexpected-failure handler, exactly
    as it did when the loop was serial.
    """
    for o in outcomes:
        if o.exception is not None and not isinstance(o.exception, DeviceError):
            raise o.exception


def submit_overlapped(side: Callable[[], S]) -> "Future[S]":
    """Start ``side`` on the shared aio-bridge executor (ISSUE 13: the
    flip path hides synchronous waits behind the same loop thread the
    async kube core runs on). The caller MUST join via
    :func:`join_overlapped` on every path — an abandoned side task
    could outlive the flip whose ordering protected it."""
    from tpu_cc_manager.k8s.aio_bridge import get_bridge

    return get_bridge().submit(side)


def join_overlapped(fut: "Future[S]", *, swallow: bool = False) -> Optional[S]:
    """Join a :func:`submit_overlapped` side task. ``swallow=True`` is
    the fail-secure path: the primary phase already failed and owns
    the error surface, so the side's own failure is logged (never
    silently lost) but not raised over the primary's."""
    if not swallow:
        return fut.result()
    try:
        return fut.result()
    except Exception:
        log.warning(
            "overlapped side task failed under a primary-phase "
            "failure; primary error wins", exc_info=True,
        )
        return None


def run_flips(
    items: Sequence[T],
    flip_one: Callable[[T], bool],
    *,
    concurrency: int,
    tracer: Tracer,
    label_of: Callable[[T], str],
    executor: Optional[ThreadPoolExecutor] = None,
    recorder: Optional[Any] = None,
) -> List[FlipOutcome]:
    """Run ``flip_one`` over ``items`` with bounded concurrency.

    ``flip_one`` returns True on success, False on a (already-logged)
    verify mismatch, and raises DeviceError on device failure. See the
    module docstring for the full serial/parallel contract.

    ``executor``: an optional PERSISTENT worker pool owned by the
    caller (the long-lived agent's engine): reusing it across
    reconciles avoids paying thread spawn/teardown — and, with the
    shared HTTP connection pool, connection churn — on every flip.
    Must be sized to at least ``concurrency`` workers (the engine sizes
    it to the unclamped knob, which upper-bounds every per-plan cap);
    the caller owns its shutdown. When None, a pool is created and torn
    down per call, the historical behavior.
    """

    def run_one(item: T) -> FlipOutcome:
        name = label_of(item)
        try:
            ok = flip_one(item)
        except DeviceError as e:
            return FlipOutcome(name, FAILED, error=str(e), exception=e)
        except BaseException as e:
            return FlipOutcome(
                name, FAILED, error=f"{type(e).__name__}: {e}", exception=e
            )
        return FlipOutcome(name, OK if ok else FAILED)

    if concurrency <= 1 or len(items) <= 1:
        # serial fail-stop: the historical per-device loop, in the
        # calling thread — trace-span order is byte-identical to the
        # pre-pipeline engine, and items after a failure stay untouched
        outcomes: List[FlipOutcome] = []
        aborted = False
        for item in items:
            if aborted:
                outcomes.append(FlipOutcome(label_of(item), SKIPPED))
                continue
            out = run_one(item)
            outcomes.append(out)
            if out.status != OK:
                aborted = True
        _note_failures(outcomes, recorder)
        _reraise_unexpected(outcomes)
        return outcomes

    abort = threading.Event()
    parent = tracer.current_span()

    def worker(item: T) -> FlipOutcome:
        # the abort check is the ONLY pre-start gate: once a worker is
        # past it the item runs its whole sequence (never cancelled
        # mid-reset), and a queued item that sees the flag is skipped
        # before it touches the device (or its gate) at all
        if abort.is_set():
            return FlipOutcome(label_of(item), SKIPPED)
        with tracer.adopt(parent):
            out = run_one(item)
        if out.status == FAILED:
            abort.set()
        return out

    with ExitStack() as stack:
        pool = executor if executor is not None else stack.enter_context(
            ThreadPoolExecutor(
                max_workers=concurrency, thread_name_prefix="cc-flip"
            )
        )
        futures = [pool.submit(worker, item) for item in items]
        # .result() outside any lock by design — see the module docstring
        # ccaudit: allow-missing-deadline(a flip worker past the abort gate is mid-device-reset and must NEVER be abandoned: timing out this join would orphan a live firmware transition — the per-step device timeouts inside the worker bound it instead)
        outcomes = [f.result() for f in futures]
    _note_failures(outcomes, recorder)
    _reraise_unexpected(outcomes)
    return outcomes
