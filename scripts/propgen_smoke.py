#!/usr/bin/env python3
"""propgen-smoke: the property-based lifecycle generator's CI gate
(ISSUE 12).

Two-sided, slo-smoke style:

1. **Green side** — a fixed seed list runs one generated episode per
   lifecycle fault family (rolling agent upgrade, attestation key
   rotation, revoked trust root + node-root forgery, policy conflict,
   evacuation drain, shard kill) plus two free seeds through the LIVE
   simlab harness and the convergence-and-invariants oracle
   (tpu_cc_manager/simlab/invariants.py). Every episode must report
   ZERO invariant violations — the reconciler contract holds under
   randomized lifecycle interleavings.
2. **Red side** — a deliberately broken episode (desired never reaches
   the converge mode) must VIOLATE, shrink deterministically, dump a
   replayable canonical ``gen-*.json``, and the reload must reproduce
   the same violation. A generator whose oracle cannot fail — or whose
   finds cannot be replayed — is not testing anything.

The shrinker's 1-minimality is additionally self-tested synthetically
(no live fleets) so a shrink regression names itself here, not inside
a 30-minute triage.

Exit 0 = all checks pass. Prints one CHECK line per assertion so a red
run names the broken contract, kind_smoke_local style.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_FAILED = []

#: (label, seed, families override, fault kinds that MUST appear) —
#: seeds picked so the seeded sub-drill choice covers both attestation
#: drills and the forgery variant; the presence CHECKs keep generator
#: drift from silently dropping coverage
EPISODES = [
    ("upgrade", 1, ["upgrade"], {"agent_upgrade"}),
    ("key-rotation", 3, ["attestation"], {"key_rotation"}),
    ("root-revoked+forge", 0, ["attestation"], {"root_revoked"}),
    ("policy-conflict", 2, ["policy"], {"policy_conflict"}),
    ("evacuation", 4, ["evacuation"], {"evacuation_drain"}),
    ("shards", 5, ["shards"], {"shard_kill"}),
    # federation (ISSUE 16): seed 2 draws the region-scoped
    # revoked-root drill (the region_attestation_latch invariant's
    # live exercise), seed 6 a region partition racing the windows
    ("federation-revoked-root", 2, ["federation"], {"root_revoked"}),
    ("federation-partition", 6, ["federation"], {"region_partition"}),
    ("free-101", 101, None, set()),
    ("free-202", 202, None, set()),
]


def check(name: str, ok: bool, detail: str = "") -> None:
    print(f"CHECK {'ok  ' if ok else 'FAIL'} {name}"
          + (f" — {detail}" if detail else ""), flush=True)
    if not ok:
        _FAILED.append(name)


def main() -> int:
    from tpu_cc_manager.simlab.propgen import (
        dump_find, generate_episode, run_episode, shrink,
    )
    from tpu_cc_manager.simlab.scenario import (
        canonical_scenario_text, load_scenario,
    )

    # ---- green side: every family through the oracle, zero violations
    for label, seed, families, must_have in EPISODES:
        doc = generate_episode(seed, families=families)
        kinds = {a.get("fault") for a in doc["actions"]
                 if a["action"] == "fault"}
        if must_have:
            check(f"{label}: episode exercises {sorted(must_have)}",
                  must_have <= kinds, f"kinds={sorted(k for k in kinds if k)}")
        result = run_episode(doc)
        check(
            f"{label}: zero invariant violations (seed {seed})",
            result.ok,
            "; ".join(f"{v.invariant}: {v.detail[:90]}"
                      for v in result.violations[:3]),
        )

    # ---- determinism: the generator is a pure function of the seed
    check("generator deterministic by seed",
          all(generate_episode(s) == generate_episode(s)
              for s in (0, 7, 101)))

    # ---- shrinker 1-minimality, synthetically (no live fleets)
    base = generate_episode(1, families=["upgrade"])
    padded = dict(base)
    padded["actions"] = sorted(base["actions"] + [
        {"at": 0.05, "action": "fault", "fault": "write_429",
         "count": 5},
        {"at": 0.1, "action": "fault", "fault": "agent_crash",
         "count": 2, "restart_after_s": 0.5},
        {"at": 0.15, "action": "fault", "fault": "watch_410"},
    ], key=lambda a: a["at"])

    def repro(d):
        kinds = [a.get("fault") for a in d["actions"]]
        return "write_429" in kinds and "agent_crash" in kinds

    shrunk, runs = shrink(padded, repro, seed=7, max_runs=64)
    kinds = [a.get("fault") for a in shrunk["actions"]]
    # minimal modulo the structural rule: the converge-driving wave is
    # never dropped, so the floor is the reproducing pair + one wave
    check("shrinker reduces to the minimal reproducing pair",
          sorted(k for k in kinds if k) == ["agent_crash", "write_429"]
          and len(shrunk["actions"]) == 3,
          f"kept {kinds} in {runs} runs")
    shrunk2, _ = shrink(padded, repro, seed=7, max_runs=64)
    check("shrinker deterministic by seed", shrunk2 == shrunk)

    # ---- red side: a violation must dump replayable and reproduce
    broken = {
        "version": 1, "name": "gen-smoke-red", "nodes": 4, "pools": 1,
        "chips_per_node": 1, "initial_mode": "off", "workers": 2,
        "qps": 0, "evidence": False, "watch_timeout_s": 2,
        "actions": [
            {"at": 0.1, "action": "set_mode", "mode": "devtools"},
        ],
        "converge": {"mode": "on", "timeout_s": 2},
    }
    result = run_episode(broken)
    check("oracle CAN fail (broken episode violates convergence)",
          any(v.invariant == "convergence" for v in result.violations),
          f"violations={[v.invariant for v in result.violations]}")
    with tempfile.TemporaryDirectory() as tmp:
        spath, rpath = dump_find(
            broken, result.violations, result.artifact,
            scenario_dir=os.path.join(tmp, "scenarios"),
            report_dir=os.path.join(tmp, "finds"),
        )
        text = open(spath).read()
        check("find is canonical scenario JSON",
              text == canonical_scenario_text(json.loads(text)))
        load_scenario(spath)  # must validate as a first-class scenario
        replay = run_episode(json.loads(text))
        check("replayed find reproduces the violation",
              any(v.invariant == "convergence"
                  for v in replay.violations))
        report = json.load(open(rpath))
        check("report carries violations + stitched timeline",
              bool(report.get("violations"))
              and "timeline" in report)

    if _FAILED:
        print(f"propgen-smoke: {len(_FAILED)} check(s) FAILED: "
              f"{_FAILED}", file=sys.stderr)
        return 1
    print("propgen-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
