#!/usr/bin/env bash
# Generate the admission webhook's serving certificate, create the
# tpu-cc-webhook-tls Secret, and substitute the CA bundle into
# deployments/manifests/webhook.yaml on stdout:
#
#   scripts/gen-webhook-certs.sh | kubectl apply -f -
#
# Self-contained alternative to cert-manager for clusters without it.
# The cert is a one-node CA signing a serving cert for the webhook
# Service DNS name; rotate by re-running (the Secret is replaced and
# the caBundle re-substituted).
set -euo pipefail

NAMESPACE="${NAMESPACE:-tpu-system}"
SERVICE="${SERVICE:-tpu-cc-webhook}"
DAYS="${DAYS:-365}"
MANIFEST="$(dirname "$0")/../deployments/manifests/webhook.yaml"

workdir="$(mktemp -d)"
trap 'rm -rf "$workdir"' EXIT

# CA
openssl req -x509 -newkey rsa:2048 -nodes -days "$DAYS" \
  -keyout "$workdir/ca.key" -out "$workdir/ca.crt" \
  -subj "/CN=${SERVICE}-ca" >/dev/null 2>&1

# serving cert for the Service DNS names
cat > "$workdir/san.cnf" <<EOF
[req]
distinguished_name = dn
req_extensions = ext
[dn]
[ext]
subjectAltName = DNS:${SERVICE}.${NAMESPACE}.svc,DNS:${SERVICE}.${NAMESPACE}.svc.cluster.local
EOF
openssl req -newkey rsa:2048 -nodes \
  -keyout "$workdir/tls.key" -out "$workdir/tls.csr" \
  -subj "/CN=${SERVICE}.${NAMESPACE}.svc" \
  -config "$workdir/san.cnf" >/dev/null 2>&1
openssl x509 -req -in "$workdir/tls.csr" -days "$DAYS" \
  -CA "$workdir/ca.crt" -CAkey "$workdir/ca.key" -CAcreateserial \
  -extensions ext -extfile "$workdir/san.cnf" \
  -out "$workdir/tls.crt" >/dev/null 2>&1

CA_BUNDLE="$(base64 < "$workdir/ca.crt" | tr -d '\n')"

# the Secret (kubectl create emits it; --dry-run keeps this script
# cluster-free so the output can be reviewed/applied atomically)
kubectl create secret tls tpu-cc-webhook-tls \
  --namespace "$NAMESPACE" \
  --cert "$workdir/tls.crt" --key "$workdir/tls.key" \
  --dry-run=client -o yaml
echo "---"
sed "s|\${CA_BUNDLE}|${CA_BUNDLE}|g" "$MANIFEST"
