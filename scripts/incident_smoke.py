#!/usr/bin/env python3
"""Incident-autopsy smoke (ISSUE 15, the incident-smoke CI job):
prove the metrics → anomaly → exemplar → profile → dump chain end to
end on live replicas, both directions —

1. ``scenarios/incident-latency-64.json`` (a scripted flip_latency
   fault injecting 0.4 s of device-reset latency mid-timeline) must
   FIRE the watchdog: ≥1 incident packet whose exemplar trace id
   resolves in the fleet-wide stitched timeline ACROSS processes
   (driver desired-write ↔ replica reconcile), and whose live-captured
   profile names the injected-latency phase (``reset``) as the hottest
   span-tagged phase.
2. ``scenarios/incident-clean-64.json`` (the same shape, no fault)
   must fire NOTHING — zero incidents — while the per-replica
   expositions (now carrying exemplar suffixes) and the merged fleet
   aggregation both stay valid.

An autopsy layer that can't demonstrate both halves is worse than
none — blind on real anomalies or crying on clean runs. Exit 0 only
when both hold.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# responsive scrape cadence for the short smoke scenarios (the lab
# default is 1 s; the baseline + anomaly windows are a few seconds)
os.environ.setdefault("TPU_CC_FLEETOBS_INTERVAL_S", "0.25")

from tpu_cc_manager.obs import validate_exposition  # noqa: E402
from tpu_cc_manager.simlab.runner import SimLab  # noqa: E402
from tpu_cc_manager.simlab.scenario import load_scenario  # noqa: E402

SCENARIO_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scenarios")

checks = []


def check(name, ok, detail=""):
    checks.append(ok)
    print(f"{'PASS' if ok else 'FAIL'} {name}"
          + (f": {detail}" if detail else ""))


def run(scenario):
    lab = SimLab(load_scenario(os.path.join(SCENARIO_DIR, scenario)))
    art = lab.run()
    return lab, art


def main():
    # ---- the anomaly half
    lab, art = run("incident-latency-64.json")
    check("latency scenario converged", art["ok"], art.get("notes") or "")
    inc = art["metrics"].get("incidents") or {}
    packets = inc.get("packets") or []
    check("watchdog fired >=1 incident", inc.get("count", 0) >= 1,
          json.dumps(inc.get("count")))
    if packets:
        p = packets[0]
        check(
            "packet carries the anomalous series + window stats",
            bool(p.get("series", {}).get("metric"))
            and isinstance(p.get("window"), dict)
            and isinstance(p.get("baseline"), dict),
            json.dumps(p.get("series")),
        )
        check("exemplar trace id resolves in the stitched timeline",
              bool(p.get("resolved_trace_ids")),
              json.dumps(p.get("exemplars"))[:200])
        check("exemplar trace stitches ACROSS processes",
              bool(p.get("cross_process_trace_ids")),
              json.dumps(p.get("resolved_trace_ids")))
        prof = p.get("profile") or {}
        phases = [ph for ph, _n in (prof.get("phase_totals") or [])]
        check(
            "profile names the injected-latency phase (reset hottest)",
            bool(phases) and phases[0] == "reset",
            json.dumps(prof.get("phase_totals"))[:160],
        )
        check("profile actually sampled", (prof.get("samples") or 0) > 0)
        check("incident capture completed in bounded time",
              0 <= (p.get("capture_s") or -1) <= 5.0,
              str(p.get("capture_s")))
    events = [e for e in lab.obs_rec.snapshot()["events"]
              if e["kind"] == "incident"]
    check("incident event landed in the flight recorder", bool(events))
    slo = art["metrics"].get("slo") or {}
    check("merged aggregation stayed valid under the anomaly",
          not slo.get("aggregation_problems"),
          str(slo.get("aggregation_problems"))[:160])

    # ---- the quiet half
    lab, art = run("incident-clean-64.json")
    check("clean scenario converged", art["ok"], art.get("notes") or "")
    inc = art["metrics"].get("incidents") or {}
    check("clean run fired ZERO incidents", inc.get("count", 0) == 0,
          json.dumps(inc)[:200])
    slo = art["metrics"].get("slo") or {}
    check("clean aggregation valid", not slo.get("aggregation_problems"))
    # the per-replica expositions now carry exemplar suffixes — every
    # one must still parse under the strict validator
    bad = 0
    for r in lab.replicas.values():
        if validate_exposition(r.metrics.render()):
            bad += 1
    check("all per-replica expositions (with exemplars) valid",
          bad == 0, f"{bad} invalid")

    print(f"\nincident-smoke: {sum(checks)}/{len(checks)} checks passed")
    return 0 if all(checks) else 1


if __name__ == "__main__":
    sys.exit(main())
